"""TD3 — twin-delayed deep deterministic policy gradient
(↔ org.deeplearning4j.rl4j's continuous-control (DDPG-family) role; TD3 is
the fixed-up successor with the three stabilizers below).

All three TD3 mechanisms, fused into two jit'd programs (critic step every
iteration; actor + polyak target update every ``policy_delay``):

1. clipped double-Q: TD target uses min(Q1', Q2')
2. delayed policy updates
3. target policy smoothing: clipped gaussian noise on the target action
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.rl.qlearning import (
    adam_init,
    adam_update,
    mlp_apply,
    mlp_init,
)
from deeplearning4j_tpu.rl.replay import ReplayBuffer


@dataclasses.dataclass
class TD3Config:
    gamma: float = 0.99
    tau: float = 0.005              # polyak for target nets
    actor_lr: float = 1e-3
    critic_lr: float = 1e-3
    policy_delay: int = 2
    policy_noise: float = 0.2       # target smoothing sigma
    noise_clip: float = 0.5
    explore_noise: float = 0.1
    batch_size: int = 128
    buffer_size: int = 100_000
    warmup_steps: int = 500
    hidden: Tuple[int, ...] = (64, 64)
    seed: int = 0


class TD3:
    """Continuous-control learner over one MDP with box actions in [-1,1]^A.

    mdp protocol: reset() -> obs; step(action: np.ndarray) ->
    (obs, reward, done, info); attributes observation_shape, action_dim.
    """

    def __init__(self, mdp, config: Optional[TD3Config] = None):
        self.mdp = mdp
        self.config = cfg = config or TD3Config()
        obs_dim = int(np.prod(mdp.observation_shape))
        self.act_dim = act_dim = mdp.action_dim

        self.params = {
            "actor": mlp_init([obs_dim, *cfg.hidden, act_dim], cfg.seed),
            "q1": mlp_init([obs_dim + act_dim, *cfg.hidden, 1], cfg.seed + 1),
            "q2": mlp_init([obs_dim + act_dim, *cfg.hidden, 1], cfg.seed + 2),
        }
        self.buffer = ReplayBuffer(cfg.buffer_size, mdp.observation_shape,
                                   seed=cfg.seed, action_shape=(act_dim,),
                                   action_dtype=np.float32)
        self._rng = np.random.default_rng(cfg.seed)
        self.total_steps = 0
        self.episode_returns: List[float] = []
        self._build()

    # -- jit programs --------------------------------------------------------

    def _build(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config

        def actor(params, obs):
            return jnp.tanh(mlp_apply(params["actor"], obs))

        def q(params, key, obs, act):
            return mlp_apply(params[key],
                             jnp.concatenate([obs, act], -1))[..., 0]

        def critic_step(params, targets, copt, rng, batch):
            obs, act, rew, nobs, done = batch

            noise = jnp.clip(
                cfg.policy_noise * jax.random.normal(rng, act.shape),
                -cfg.noise_clip, cfg.noise_clip)
            next_act = jnp.clip(actor(targets, nobs) + noise, -1.0, 1.0)
            tq = jnp.minimum(q(targets, "q1", nobs, next_act),
                             q(targets, "q2", nobs, next_act))
            target = rew + cfg.gamma * (1.0 - done) * tq

            def loss_fn(critics):
                p = {**params, **critics}
                l1 = jnp.mean(jnp.square(q(p, "q1", obs, act) - target))
                l2 = jnp.mean(jnp.square(q(p, "q2", obs, act) - target))
                return l1 + l2

            critics = {"q1": params["q1"], "q2": params["q2"]}
            loss, grads = jax.value_and_grad(loss_fn)(critics)
            critics, copt = adam_update(critics, grads, copt, cfg.critic_lr)
            return {**params, **critics}, copt, loss

        def actor_step(params, targets, aopt, obs):
            def loss_fn(actor_p):
                a = actor({"actor": actor_p["actor"]}, obs)
                return -jnp.mean(q(params, "q1", obs, a))

            actor_p = {"actor": params["actor"]}
            loss, grads = jax.value_and_grad(loss_fn)(actor_p)
            actor_p, aopt = adam_update(actor_p, grads, aopt, cfg.actor_lr)
            params = {**params, **actor_p}
            targets = jax.tree_util.tree_map(
                lambda t, p: (1 - cfg.tau) * t + cfg.tau * p, targets, params)
            return params, targets, aopt, loss

        self.params = jax.tree_util.tree_map(jnp.asarray, self.params)
        self.targets = jax.tree_util.tree_map(lambda a: a.copy(), self.params)
        self._copt = adam_init({"q1": self.params["q1"],
                                "q2": self.params["q2"]})
        self._aopt = adam_init({"actor": self.params["actor"]})
        self._jit_critic = jax.jit(critic_step)
        self._jit_actor = jax.jit(actor_step)
        self._jit_act = jax.jit(actor)
        self._key = jax.random.key(cfg.seed)

    # -- interaction ---------------------------------------------------------

    def action(self, obs, *, explore: bool = True) -> np.ndarray:
        import jax

        a = np.asarray(jax.device_get(self._jit_act(
            {"actor": self.params["actor"]},
            np.asarray(obs, np.float32)[None])))[0]
        if explore:
            a = a + self._rng.normal(0, self.config.explore_noise, a.shape)
        return np.clip(a, -1.0, 1.0).astype(np.float32)

    def train(self, env_steps: int) -> None:
        """Resumable: an episode in flight from a previous train() call
        continues — chunked train(n)+train(m) equals train(n+m)."""
        import jax

        cfg = self.config
        if getattr(self, "_obs", None) is None:
            self._obs = self.mdp.reset()
            self._acc = 0.0
        obs, acc = self._obs, self._acc
        for _ in range(env_steps):
            if self.total_steps < cfg.warmup_steps:
                act = self._rng.uniform(-1, 1, self.act_dim).astype(np.float32)
            else:
                act = self.action(obs)
            nobs, rew, done, info = self.mdp.step(act)
            acc += rew
            # time-limit truncations bootstrap; real terminals do not
            store_done = 0.0 if info.get("truncated") else float(done)
            self.buffer.add(obs, act, rew, nobs, store_done)
            obs = nobs
            self.total_steps += 1
            if done:
                self.episode_returns.append(acc)
                acc = 0.0
                obs = self.mdp.reset()

            if (self.total_steps >= cfg.warmup_steps
                    and len(self.buffer) >= cfg.batch_size):
                batch = self.buffer.sample(cfg.batch_size)
                self._key, sub = jax.random.split(self._key)
                self.params, self._copt, _ = self._jit_critic(
                    self.params, self.targets, self._copt, sub, batch)
                if self.total_steps % cfg.policy_delay == 0:
                    self.params, self.targets, self._aopt, _ = \
                        self._jit_actor(self.params, self.targets, self._aopt,
                                        batch[0])
        self._obs, self._acc = obs, acc

    def evaluate(self, episodes: int = 5) -> float:
        # evaluation drives the same (stateful) env — the training episode
        # in flight is void after this, so drop it rather than resume a
        # mismatched (obs, env-state) pair
        self._obs = None
        total = 0.0
        for _ in range(episodes):
            obs = self.mdp.reset()
            done = False
            while not done:
                obs, rew, done, _ = self.mdp.step(
                    self.action(obs, explore=False))
                total += rew
        return total / episodes
