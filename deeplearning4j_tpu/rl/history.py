"""Frame preprocessing + stacking for pixel RL (↔ RL4J HistoryProcessor +
the ALE/malmo MDP wrappers).

ref: org.deeplearning4j.rl4j.util.HistoryProcessor (grayscale, rescale to
84x84, stack the last N frames, frame-skip with action repeat) and
org.deeplearning4j.rl4j.mdp.ale.ALEMDP. The Atari emulator itself is an
external native dependency (ale-py / Stella) not present here; the
connector half — everything between a raw-frame-producing env and the DQN
agent — is implemented in full and wraps ANY MDP whose observations are
[H, W] or [H, W, C] uint8/float frames (an ale-py or gymnasium Atari env
plugs straight in; tests use a synthetic frame env).

DeepMind-standard pipeline, matching the reference's defaults:
grayscale → bilinear resize to ``size`` → max-pool over the last two raw
frames (flicker removal) → repeat each action ``skip`` times → stack the
last ``stack`` processed frames into the [stack, H, W] observation.
"""

from __future__ import annotations

from collections import deque
from typing import Optional, Tuple

import numpy as np


def to_grayscale(frame: np.ndarray) -> np.ndarray:
    """[H,W] passthrough; [H,W,3] ITU-R 601 luma; [H,W,1] squeeze."""
    if frame.ndim == 2:
        return frame.astype(np.float32)
    if frame.shape[-1] == 1:
        return frame[..., 0].astype(np.float32)
    f = frame.astype(np.float32)
    return 0.299 * f[..., 0] + 0.587 * f[..., 1] + 0.114 * f[..., 2]


def resize_bilinear(img: np.ndarray, size: Tuple[int, int]) -> np.ndarray:
    """Dependency-free bilinear resize of a [H,W] image (align_corners=False
    convention, the cv2/PIL default)."""
    h, w = img.shape
    oh, ow = size
    if (h, w) == (oh, ow):
        return img.astype(np.float32)
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :]
    img = img.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


class HistoryProcessor:
    """↔ RL4J HistoryProcessor: per-frame preprocessing + rolling stack.

    ``add(frame)`` ingests a raw frame; ``history()`` returns the current
    [stack, H, W] float32 observation (oldest first), zero-padded until
    ``stack`` frames have been seen since the last ``reset()``.
    """

    def __init__(self, stack: int = 4, size: Tuple[int, int] = (84, 84),
                 scale: float = 1.0 / 255.0):
        self.stack = stack
        self.size = tuple(size)
        self.scale = scale
        self._frames: deque = deque(maxlen=stack)

    def process(self, frame: np.ndarray) -> np.ndarray:
        return (resize_bilinear(to_grayscale(np.asarray(frame)), self.size)
                * self.scale).astype(np.float32)

    def add(self, frame: np.ndarray) -> None:
        self._frames.append(self.process(frame))

    def reset(self) -> None:
        self._frames.clear()

    def history(self) -> np.ndarray:
        n = len(self._frames)
        if n == 0:
            raise RuntimeError("history() before any add()")
        pad = [np.zeros(self.size, np.float32)] * (self.stack - n)
        return np.stack(pad + list(self._frames))


class FrameStackEnv:
    """ALE-style MDP wrapper: action-repeat + flicker max-pool + history.

    Wraps any env with ``reset() -> frame`` and
    ``step(a) -> (frame, reward, done, info)`` where ``frame`` is an image;
    emits [stack, H, W] float32 observations. ``skip``: each agent action is
    repeated ``skip`` emulator steps, rewards summed, and the observation is
    the elementwise max of the last two raw frames (the DeepMind/ALE
    flicker workaround the reference inherits).
    """

    def __init__(self, env, *, stack: int = 4, skip: int = 4,
                 size: Tuple[int, int] = (84, 84),
                 scale: float = 1.0 / 255.0):
        self.env = env
        self.skip = max(1, skip)
        self.proc = HistoryProcessor(stack=stack, size=size, scale=scale)
        # expose the MDP-protocol surface so learners can wrap this env
        # directly (they read action_count/observation_shape, mdp.py:21-22)
        n = getattr(env, "action_count", None) or getattr(
            env, "action_space_n", None)
        self.action_space_n: Optional[int] = n
        if n is not None:
            self.action_count = int(n)
        self.observation_shape = (stack, *self.proc.size)

    def reset(self) -> np.ndarray:
        frame = self.env.reset()
        self.proc.reset()
        self.proc.add(frame)
        return self.proc.history()

    def step(self, action):
        total = 0.0
        done = False
        info: dict = {}
        last_two = deque(maxlen=2)
        frame = None
        for _ in range(self.skip):
            frame, r, done, info = self.env.step(action)
            total += float(r)
            last_two.append(np.asarray(frame, np.float32))
            if done:
                break
        pooled = (np.maximum(last_two[0], last_two[1])
                  if len(last_two) == 2 else last_two[0])
        self.proc.add(pooled)
        return self.proc.history(), total, done, info


class SyntheticFrameEnv:
    """Tiny deterministic frame-producing MDP for connector tests: a bright
    square whose position advances each step; reward 1 when the agent's
    action matches the square's parity; episode of fixed length."""

    action_space_n = 2

    def __init__(self, shape=(30, 40, 3), episode_len: int = 12):
        self.shape = shape
        self.episode_len = episode_len
        self._t = 0

    def _frame(self) -> np.ndarray:
        f = np.zeros(self.shape, np.uint8)
        x = (3 * self._t) % (self.shape[1] - 6)
        f[5:11, x:x + 6] = 255
        return f

    def reset(self) -> np.ndarray:
        self._t = 0
        return self._frame()

    def step(self, action):
        self._t += 1
        reward = 1.0 if int(action) == self._t % 2 else 0.0
        return self._frame(), reward, self._t >= self.episode_len, {}
