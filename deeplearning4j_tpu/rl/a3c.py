"""A3C (↔ org.deeplearning4j.rl4j.learning.async.a3c.A3CDiscrete +
AsyncGlobal/AsyncThread workers).

TPU-first redesign of the reference's worker model: rl4j runs JVM actor
THREADS that race gradient updates into a shared global network (Hogwild
style). Races buy nothing on an accelerator whose update is one fused XLA
program — so the workers here are a VECTOR of environments stepped in
lockstep on the host, with one BATCHED jit'd forward serving every
worker's policy and one fused update consuming all workers' n-step
rollouts per iteration. Same estimator (n-step advantage actor-critic
with entropy bonus), same worker-diversity effect (decorrelated
experience from K parallel actors), deterministic instead of racy.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.rl.qlearning import (
    adam_init,
    adam_update,
    mlp_apply,
    mlp_init,
)


@dataclasses.dataclass
class A3CConfig:
    gamma: float = 0.99
    learning_rate: float = 7e-4
    n_steps: int = 8           # rollout length per worker per update
    num_workers: int = 8       # ↔ rl4j numThreads
    value_coef: float = 0.5
    entropy_coef: float = 0.01
    hidden: Tuple[int, ...] = (64,)
    seed: int = 0


class A3CDiscrete:
    """Batched-worker advantage actor-critic for discrete actions.

    ``mdp_factory(worker_index) -> MDP`` builds one env per worker (the
    reference's per-thread MDP instances; pass different seeds for
    decorrelation).
    """

    def __init__(self, mdp_factory: Callable[[int], object],
                 config: Optional[A3CConfig] = None):
        self.config = cfg = config or A3CConfig()
        self.envs = [mdp_factory(i) for i in range(cfg.num_workers)]
        obs_dim = int(np.prod(self.envs[0].observation_shape))
        self.action_count = self.envs[0].action_count
        self.params = {
            "trunk": mlp_init([obs_dim, *cfg.hidden], cfg.seed),
            "pi": mlp_init([cfg.hidden[-1], self.action_count], cfg.seed + 1),
            "v": mlp_init([cfg.hidden[-1], 1], cfg.seed + 2),
        }
        self._rng = np.random.default_rng(cfg.seed)
        self._obs = np.stack([e.reset() for e in self.envs])
        self.episode_returns: List[float] = []
        self._acc = np.zeros(cfg.num_workers)
        self._build()

    def _build(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config

        def forward(params, obs):          # obs [K, D] — every worker at once
            h = jnp.maximum(mlp_apply(params["trunk"], obs), 0.0)
            return mlp_apply(params["pi"], h), mlp_apply(params["v"], h)[..., 0]

        def loss_fn(params, obs, actions, returns):
            logits, value = forward(params, obs)
            logp = jax.nn.log_softmax(logits)
            logp_a = jnp.take_along_axis(logp, actions[:, None], 1)[:, 0]
            adv = returns - value
            policy_loss = -jnp.mean(logp_a * jax.lax.stop_gradient(adv))
            value_loss = jnp.mean(jnp.square(adv))
            entropy = -jnp.mean(jnp.sum(jnp.exp(logp) * logp, -1))
            return (policy_loss + cfg.value_coef * value_loss
                    - cfg.entropy_coef * entropy)

        def step(params, opt, obs, actions, returns):
            loss, grads = jax.value_and_grad(loss_fn)(params, obs, actions,
                                                      returns)
            params, opt = adam_update(params, grads, opt, cfg.learning_rate)
            return params, opt, loss

        self._opt = adam_init(self.params)
        self._jit_step = jax.jit(step, donate_argnums=(0, 1))
        self._jit_forward = jax.jit(forward)

    # -- acting --------------------------------------------------------------

    def _act(self, obs_batch):
        import jax

        logits, values = self._jit_forward(self.params,
                                           obs_batch.astype(np.float32))
        logits = np.asarray(jax.device_get(logits))
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        acts = np.array([self._rng.choice(self.action_count, p=pi)
                         for pi in p])
        return acts, np.asarray(jax.device_get(values))

    def train_iteration(self) -> float:
        """One update: every worker contributes an n-step rollout."""
        cfg = self.config
        K, T = cfg.num_workers, cfg.n_steps
        obs_buf = np.zeros((T, K) + (self._obs.shape[1],), np.float32)
        act_buf = np.zeros((T, K), np.int64)
        rew_buf = np.zeros((T, K), np.float32)
        done_buf = np.zeros((T, K), np.float32)

        for t in range(T):
            acts, _ = self._act(self._obs)
            obs_buf[t] = self._obs
            act_buf[t] = acts
            for k, env in enumerate(self.envs):
                nobs, r, done, _ = env.step(int(acts[k]))
                rew_buf[t, k] = r
                done_buf[t, k] = float(done)
                self._acc[k] += r
                if done:
                    self.episode_returns.append(self._acc[k])
                    self._acc[k] = 0.0
                    nobs = env.reset()
                self._obs[k] = nobs

        import jax

        # V(s_T) bootstrap per worker: value head only (no policy sampling —
        # a value query must not perturb the exploration RNG stream)
        _, boot = self._jit_forward(self.params, self._obs.astype(np.float32))
        boot = np.asarray(jax.device_get(boot))
        rets = np.zeros((T, K), np.float32)
        running = boot.copy()
        for t in reversed(range(T)):
            running = rew_buf[t] + cfg.gamma * running * (1.0 - done_buf[t])
            rets[t] = running

        self.params, self._opt, loss = self._jit_step(
            self.params, self._opt,
            obs_buf.reshape(T * K, -1), act_buf.reshape(T * K),
            rets.reshape(T * K))
        return float(jax.device_get(loss))

    def train(self, iterations: int) -> List[float]:
        return [self.train_iteration() for _ in range(iterations)]

    def policy_action(self, obs) -> int:
        import jax

        logits, _ = self._jit_forward(self.params,
                                      np.asarray(obs, np.float32)[None])
        return int(np.argmax(np.asarray(jax.device_get(logits))[0]))
