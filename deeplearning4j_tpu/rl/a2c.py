"""Advantage actor-critic (↔ org.deeplearning4j.rl4j.learning.async.a3c.A3CDiscrete).

The reference runs asynchronous JVM actor threads sharing a global net
(A3C); on TPU the synchronous batched variant (A2C) is the idiomatic
equivalent — n-step rollouts collected on the host, ONE jit'd update fusing
policy gradient + value loss + entropy bonus. (Async gradient races buy
nothing when the update itself is a single fused device step.)
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.rl.qlearning import mlp_apply, mlp_init


@dataclasses.dataclass
class A2CConfig:
    gamma: float = 0.99
    learning_rate: float = 7e-4
    n_steps: int = 16
    value_coef: float = 0.5
    entropy_coef: float = 0.01
    hidden: Tuple[int, ...] = (64,)
    seed: int = 0


class A2C:
    """Shared-trunk actor-critic over one MDP instance."""

    def __init__(self, mdp, config: Optional[A2CConfig] = None):
        self.mdp = mdp
        self.config = config or A2CConfig()
        obs_dim = int(np.prod(mdp.observation_shape))
        cfg = self.config
        self.params = {
            "trunk": mlp_init([obs_dim, *cfg.hidden], cfg.seed),
            "pi": mlp_init([cfg.hidden[-1], mdp.action_count], cfg.seed + 1),
            "v": mlp_init([cfg.hidden[-1], 1], cfg.seed + 2),
        }
        self._rng = np.random.default_rng(cfg.seed)
        self._build()

    def _build(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config

        def forward(params, obs):
            h = mlp_apply(params["trunk"], obs)
            h = jnp.maximum(h, 0.0)
            logits = mlp_apply(params["pi"], h)
            value = mlp_apply(params["v"], h)[..., 0]
            return logits, value

        def loss_fn(params, obs, actions, returns):
            logits, value = forward(params, obs)
            logp = jax.nn.log_softmax(logits)
            logp_a = jnp.take_along_axis(logp, actions[:, None], 1)[:, 0]
            adv = returns - value
            policy_loss = -jnp.mean(logp_a * jax.lax.stop_gradient(adv))
            value_loss = jnp.mean(jnp.square(adv))
            entropy = -jnp.mean(jnp.sum(jnp.exp(logp) * logp, -1))
            return (policy_loss + cfg.value_coef * value_loss
                    - cfg.entropy_coef * entropy)

        def step(params, opt, t, obs, actions, returns):
            loss, grads = jax.value_and_grad(loss_fn)(params, obs, actions, returns)
            m, v = opt
            b1, b2, eps = 0.9, 0.999, 1e-8
            m = jax.tree_util.tree_map(lambda a, g: b1 * a + (1 - b1) * g, m, grads)
            v = jax.tree_util.tree_map(lambda a, g: b2 * a + (1 - b2) * g * g, v, grads)
            t = t + 1
            params = jax.tree_util.tree_map(
                lambda p, a, bb: p - cfg.learning_rate * (a / (1 - b1**t))
                / (jnp.sqrt(bb / (1 - b2**t)) + eps),
                params, m, v)
            return params, (m, v), t, loss

        z = jax.tree_util.tree_map(jnp.zeros_like,
                                   jax.tree_util.tree_map(jnp.asarray, self.params))
        self._opt = (z, jax.tree_util.tree_map(jnp.zeros_like, z))
        self._t = jnp.zeros((), jnp.int32)
        self._jit_step = jax.jit(step, donate_argnums=(0, 1))
        self._jit_forward = jax.jit(forward)

    def _policy(self, obs) -> Tuple[int, float]:
        import jax

        logits, value = self._jit_forward(self.params,
                                          np.asarray(obs, np.float32)[None])
        logits = np.asarray(jax.device_get(logits))[0]
        p = np.exp(logits - logits.max())
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p)), float(value[0])

    def act_greedy(self, obs) -> int:
        import jax

        logits, _ = self._jit_forward(self.params,
                                      np.asarray(obs, np.float32)[None])
        return int(np.argmax(np.asarray(jax.device_get(logits))[0]))

    def play(self) -> float:
        obs = self.mdp.reset()
        total, done = 0.0, False
        while not done:
            obs, r, done, _ = self.mdp.step(self.act_greedy(obs))
            total += r
        return total

    def train(self, *, max_steps: int = 10_000,
              listeners: Optional[List[Callable]] = None) -> List[float]:
        cfg = self.config
        episode_rewards: List[float] = []
        obs = self.mdp.reset()
        ep_reward = 0.0
        step_i = 0
        while step_i < max_steps:
            # n-step rollout
            traj_obs, traj_act, traj_rew, traj_done = [], [], [], []
            for _ in range(cfg.n_steps):
                a, _ = self._policy(obs)
                nxt, r, done, _ = self.mdp.step(a)
                traj_obs.append(obs)
                traj_act.append(a)
                traj_rew.append(r)
                traj_done.append(done)
                ep_reward += r
                step_i += 1
                obs = nxt
                if done:
                    episode_rewards.append(ep_reward)
                    for lst in listeners or []:
                        lst(len(episode_rewards), ep_reward)
                    ep_reward = 0.0
                    obs = self.mdp.reset()
            # bootstrap from the value of the final state
            _, boot = self._policy(obs)
            returns = np.zeros(len(traj_rew), np.float32)
            run = 0.0 if traj_done[-1] else boot
            for i in reversed(range(len(traj_rew))):
                run = traj_rew[i] + cfg.gamma * run * (0.0 if traj_done[i] else 1.0)
                # a done inside the window resets the return beyond it
                returns[i] = run
            self.params, self._opt, self._t, _ = self._jit_step(
                self.params, self._opt, self._t,
                np.asarray(traj_obs, np.float32),
                np.asarray(traj_act, np.int32), returns)
        return episode_rewards
