"""Deep Q-learning (↔ org.deeplearning4j.rl4j.learning.sync.qlearning
.discrete.QLearningDiscrete + QLConfiguration).

TPU-first shape: the reference's learner calls network.fit per minibatch
through the full per-op stack; here the TD step — forward on obs AND next
obs, (double-)DQN target, Huber loss, Adam update — is ONE jit'd XLA
program with donated params; the host loop only steps the environment and
fills the replay buffer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.rl.policy import EpsGreedyPolicy
from deeplearning4j_tpu.rl.replay import ReplayBuffer


def mlp_init(sizes: Sequence[int], seed: int = 0):
    """Small MLP (relu hidden) param pytree."""
    rs = np.random.RandomState(seed)
    params = []
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        w = (rs.randn(a, b) * np.sqrt(2.0 / a)).astype(np.float32)
        params.append({"w": w, "b": np.zeros(b, np.float32)})
    return params


def mlp_apply(params, x):
    import jax.numpy as jnp

    h = x.reshape(x.shape[0], -1)  # flatten multi-dim observations
    for i, layer in enumerate(params):
        h = h @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            h = jnp.maximum(h, 0.0)
    return h


@dataclasses.dataclass
class QLearningConfig:
    """↔ QLearning.QLConfiguration."""

    gamma: float = 0.99
    learning_rate: float = 1e-3
    batch_size: int = 64
    replay_capacity: int = 10_000
    warmup_steps: int = 200
    target_update_every: int = 250
    train_every: int = 1
    double_dqn: bool = True
    eps_start: float = 1.0
    eps_min: float = 0.05
    eps_anneal_steps: int = 3000
    hidden: Tuple[int, ...] = (64, 64)
    seed: int = 0


class QLearningDiscrete:
    """DQN learner over any MDP with discrete actions.

    network: optional (init_fn() -> params, apply_fn(params, obs) -> q)
    pair; default is an MLP sized from the MDP.
    """

    def __init__(self, mdp, config: Optional[QLearningConfig] = None,
                 network: Optional[Tuple[Callable, Callable]] = None):
        self.mdp = mdp
        self.config = config or QLearningConfig()
        obs_dim = int(np.prod(mdp.observation_shape))
        if network is None:
            sizes = [obs_dim, *self.config.hidden, mdp.action_count]
            self._init_fn = lambda: mlp_init(sizes, self.config.seed)
            self._apply_fn = mlp_apply
        else:
            self._init_fn, self._apply_fn = network
        self.params = self._init_fn()
        self.target_params = self.params
        self.replay = ReplayBuffer(self.config.replay_capacity,
                                   mdp.observation_shape, self.config.seed)
        self.policy = EpsGreedyPolicy(self.config.eps_start, self.config.eps_min,
                                      self.config.eps_anneal_steps,
                                      self.config.seed)
        self._build()

    def _build(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        apply_fn = self._apply_fn

        def td_loss(params, target_params, obs, actions, rewards, next_obs, dones):
            q = apply_fn(params, obs)
            q_sel = jnp.take_along_axis(q, actions[:, None], 1)[:, 0]
            q_next_t = apply_fn(target_params, next_obs)
            if cfg.double_dqn:
                a_star = jnp.argmax(apply_fn(params, next_obs), -1)
                q_next = jnp.take_along_axis(q_next_t, a_star[:, None], 1)[:, 0]
            else:
                q_next = jnp.max(q_next_t, -1)
            target = rewards + cfg.gamma * (1.0 - dones) * q_next
            err = q_sel - jax.lax.stop_gradient(target)
            # Huber
            return jnp.mean(jnp.where(jnp.abs(err) < 1.0, 0.5 * err * err,
                                      jnp.abs(err) - 0.5))

        def adam_init(params):
            z = jax.tree_util.tree_map(jnp.zeros_like, params)
            return (z, jax.tree_util.tree_map(jnp.zeros_like, params))

        def step(params, opt, t, target_params, batch):
            loss, grads = jax.value_and_grad(td_loss)(params, target_params, *batch)
            m, v = opt
            b1, b2, eps = 0.9, 0.999, 1e-8
            m = jax.tree_util.tree_map(lambda a, g: b1 * a + (1 - b1) * g, m, grads)
            v = jax.tree_util.tree_map(lambda a, g: b2 * a + (1 - b2) * g * g, v, grads)
            t = t + 1
            mh = jax.tree_util.tree_map(lambda a: a / (1 - b1**t), m)
            vh = jax.tree_util.tree_map(lambda a: a / (1 - b2**t), v)
            params = jax.tree_util.tree_map(
                lambda p, a, bb: p - cfg.learning_rate * a / (jnp.sqrt(bb) + eps),
                params, mh, vh)
            return params, (m, v), t, loss

        # no donation: target_params aliases params buffers between target
        # syncs, and donating them would invalidate the target network.
        self._jit_step = jax.jit(step)
        self._jit_q = jax.jit(apply_fn)
        self._opt = adam_init(jax.tree_util.tree_map(jnp.asarray, self.params))
        self._t = jnp.zeros((), jnp.int32)

    def q_values(self, obs: np.ndarray) -> np.ndarray:
        import jax

        return np.asarray(jax.device_get(
            self._jit_q(self.params, np.asarray(obs, np.float32)[None]))[0])

    def play(self, greedy: bool = True) -> float:
        """One evaluation episode; returns total reward."""
        obs = self.mdp.reset()
        total, done = 0.0, False
        while not done:
            q = self.q_values(obs)
            a = int(np.argmax(q))
            obs, r, done, _ = self.mdp.step(a)
            total += r
        return total

    def train(self, *, max_steps: int = 10_000,
              listeners: Optional[List[Callable]] = None) -> List[float]:
        """Environment-step loop; returns per-episode rewards."""
        import jax

        cfg = self.config
        episode_rewards: List[float] = []
        obs = self.mdp.reset()
        ep_reward = 0.0
        for step_i in range(max_steps):
            q = self.q_values(obs)
            action = self.policy.select(q, step_i)
            next_obs, reward, done, _ = self.mdp.step(action)
            self.replay.add(obs, action, reward, next_obs, done)
            ep_reward += reward
            obs = next_obs
            if done:
                episode_rewards.append(ep_reward)
                for lst in listeners or []:
                    lst(len(episode_rewards), ep_reward)
                ep_reward = 0.0
                obs = self.mdp.reset()
            if (len(self.replay) >= cfg.warmup_steps
                    and step_i % cfg.train_every == 0):
                batch = self.replay.sample(cfg.batch_size)
                self.params, self._opt, self._t, _ = self._jit_step(
                    self.params, self._opt, self._t, self.target_params,
                    tuple(np.asarray(b) for b in batch))
            if step_i % cfg.target_update_every == 0:
                self.target_params = jax.tree_util.tree_map(
                    lambda x: x, self.params)
        return episode_rewards


def adam_init(params):
    """Shared Adam state for the rl learners (a2c/a3c/td3): (m, v, t)."""
    import jax
    import jax.numpy as jnp

    z = jax.tree_util.tree_map(jnp.zeros_like,
                               jax.tree_util.tree_map(jnp.asarray, params))
    return z, jax.tree_util.tree_map(jnp.zeros_like, z), jnp.zeros((), jnp.int32)


def adam_update(params, grads, opt, lr, *, b1=0.9, b2=0.999, eps=1e-8):
    """One bias-corrected Adam step; returns (params, opt). jit-safe."""
    import jax
    import jax.numpy as jnp

    m, v, t = opt
    t = t + 1
    m = jax.tree_util.tree_map(lambda a, g: b1 * a + (1 - b1) * g, m, grads)
    v = jax.tree_util.tree_map(lambda a, g: b2 * a + (1 - b2) * g * g, v, grads)
    params = jax.tree_util.tree_map(
        lambda p, a, bb: p - lr * (a / (1 - b1 ** t))
        / (jnp.sqrt(bb / (1 - b2 ** t)) + eps), params, m, v)
    return params, (m, v, t)
