"""MDP interface + toy environments (↔ org.deeplearning4j.rl4j.mdp.MDP and
the gym/malmo/ale connectors, SURVEY §2.7).

The reference binds external simulators (gym-java-client etc.); in this
zero-egress build the interface is the deliverable and two classic pure-
numpy environments back the tests. Any object with reset/step/action_count/
observation_shape plugs into the learners (gymnasium adapters drop in the
same way the reference's connectors did).
"""

from __future__ import annotations

from typing import Any, Optional, Protocol, Tuple

import numpy as np


class MDP(Protocol):
    """↔ org.deeplearning4j.rl4j.mdp.MDP<O, A, AS>."""

    action_count: int
    observation_shape: Tuple[int, ...]

    def reset(self) -> np.ndarray: ...

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, dict]: ...


class CartPole:
    """Classic cart-pole balancing (Barto-Sutton-Anderson dynamics; the same
    task rl4j's gym examples lead with), pure numpy."""

    action_count = 2
    observation_shape = (4,)

    def __init__(self, seed: int = 0, max_steps: int = 200):
        self._rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self._state = None
        self._t = 0

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, 4)
        self._t = 0
        return self._state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, th, th_dot = self._state
        force = 10.0 if action == 1 else -10.0
        g, mc, mp, l, tau = 9.8, 1.0, 0.1, 0.5, 0.02
        total = mc + mp
        costh, sinth = np.cos(th), np.sin(th)
        temp = (force + mp * l * th_dot**2 * sinth) / total
        th_acc = (g * sinth - costh * temp) / (l * (4.0 / 3.0 - mp * costh**2 / total))
        x_acc = temp - mp * l * th_acc * costh / total
        x += tau * x_dot
        x_dot += tau * x_acc
        th += tau * th_dot
        th_dot += tau * th_acc
        self._state = np.array([x, x_dot, th, th_dot])
        self._t += 1
        failed = bool(abs(x) > 2.4 or abs(th) > 12 * np.pi / 180)
        truncated = bool(self._t >= self.max_steps and not failed)
        # `truncated` marks a time-limit cut, NOT a terminal state — learners
        # must keep bootstrapping through it (TD target ≠ reward alone).
        return (self._state.astype(np.float32), 1.0, failed or truncated,
                {"truncated": truncated})


class Corridor:
    """Deterministic 1-D corridor: start left, goal right; +1 at the goal,
    small step penalty. Solvable quickly — the convergence-sanity
    environment for learner tests (SURVEY §4 tiny-dataset pattern)."""

    def __init__(self, length: int = 8, max_steps: int = 50):
        self.length = length
        self.max_steps = max_steps
        self.action_count = 2  # 0 = left, 1 = right
        self.observation_shape = (length,)
        self._pos = 0
        self._t = 0

    def _obs(self) -> np.ndarray:
        v = np.zeros(self.length, np.float32)
        v[self._pos] = 1.0
        return v

    def reset(self) -> np.ndarray:
        self._pos = 0
        self._t = 0
        return self._obs()

    def step(self, action: int):
        self._pos = max(0, self._pos - 1) if action == 0 else \
            min(self.length - 1, self._pos + 1)
        self._t += 1
        at_goal = self._pos == self.length - 1
        reward = 1.0 if at_goal else -0.01
        truncated = bool(self._t >= self.max_steps and not at_goal)
        return self._obs(), reward, bool(at_goal or truncated), \
            {"truncated": truncated}


class Pendulum:
    """Classic torque-limited pendulum swing-up (the continuous-control
    staple rl4j's gym connector exposed). Box action in [-1,1]^1, scaled to
    ±2 N·m torque. Episode is a 200-step time-limit truncation."""

    observation_shape = (3,)
    action_dim = 1

    def __init__(self, seed: int = 0, max_steps: int = 200):
        self._rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self._th = 0.0
        self._thdot = 0.0
        self._t = 0

    def _obs(self) -> np.ndarray:
        return np.array([np.cos(self._th), np.sin(self._th),
                         self._thdot / 8.0], np.float32)

    def reset(self) -> np.ndarray:
        self._th = self._rng.uniform(-np.pi, np.pi)
        self._thdot = self._rng.uniform(-1.0, 1.0)
        self._t = 0
        return self._obs()

    def step(self, action):
        g, m, l, dt = 10.0, 1.0, 1.0, 0.05
        u = 2.0 * float(np.clip(np.asarray(action).ravel()[0], -1.0, 1.0))
        th = ((self._th + np.pi) % (2 * np.pi)) - np.pi  # normalized angle
        cost = th ** 2 + 0.1 * self._thdot ** 2 + 0.001 * u ** 2
        self._thdot += (3 * g / (2 * l) * np.sin(self._th)
                        + 3.0 / (m * l ** 2) * u) * dt
        self._thdot = float(np.clip(self._thdot, -8.0, 8.0))
        self._th += self._thdot * dt
        self._t += 1
        truncated = self._t >= self.max_steps
        return self._obs(), -float(cost), truncated, {"truncated": truncated}


class GymEnv:
    """↔ rl4j-gym's GymEnv connector: adapt a Gymnasium/Gym environment to
    this package's MDP protocol (reset() -> obs; step(a) -> (obs, reward,
    done, info) with info['truncated'] marking time-limit cuts).

    The env object can be passed directly (duck-typed) or built by name
    when the ``gymnasium`` package is installed; this environment ships
    without it, so name-construction raises a clear error instead of
    importing at module load."""

    def __init__(self, env=None, *, name: Optional[str] = None, seed: int = 0):
        if env is None:
            if name is None:
                raise ValueError("need an env object or a name")
            try:
                import gymnasium
            except ImportError as e:  # pragma: no cover - gated dependency
                raise ImportError(
                    "gymnasium is not installed; pass a constructed env "
                    "object instead of a name") from e
            env = gymnasium.make(name)
        self.env = env
        self._seed = seed
        space = getattr(env, "action_space", None)
        if space is not None and hasattr(space, "n"):
            self.action_count = int(space.n)
        elif space is not None and hasattr(space, "shape"):
            self.action_dim = int(np.prod(space.shape))
        obs_space = getattr(env, "observation_space", None)
        if obs_space is not None and hasattr(obs_space, "shape"):
            self.observation_shape = tuple(obs_space.shape)

    def reset(self) -> np.ndarray:
        out = self.env.reset(seed=self._seed) if _accepts_seed(self.env) \
            else self.env.reset()
        self._seed = None if self._seed is None else self._seed + 1
        obs = out[0] if isinstance(out, tuple) else out
        return np.asarray(obs, np.float32)

    def step(self, action):
        out = self.env.step(action)
        if len(out) == 5:  # gymnasium: obs, reward, terminated, truncated, info
            obs, rew, term, trunc, info = out
            info = dict(info or {})
            info["truncated"] = bool(trunc)
            return (np.asarray(obs, np.float32), float(rew),
                    bool(term or trunc), info)
        obs, rew, done, info = out  # classic gym
        info = dict(info or {})
        info.setdefault("truncated",
                        bool(info.get("TimeLimit.truncated", False)))
        return np.asarray(obs, np.float32), float(rew), bool(done), info


def _accepts_seed(env) -> bool:
    import inspect

    try:
        return "seed" in inspect.signature(env.reset).parameters
    except (TypeError, ValueError):
        return False
