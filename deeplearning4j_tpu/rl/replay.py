"""Experience replay (↔ org.deeplearning4j.rl4j.learning.sync.ExpReplay).

Preallocated numpy ring buffer; sampling returns dense batches ready for
one jit'd learner step (the reference boxes each Transition; here storage
is columnar from the start so the device batch is a set of views)."""

from __future__ import annotations

from typing import Tuple

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, observation_shape: Tuple[int, ...],
                 seed: int = 0, *, action_shape: Tuple[int, ...] = (),
                 action_dtype=np.int32):
        """``action_shape=()``/int32 for discrete learners (DQN);
        continuous learners (TD3/DDPG) pass a vector shape + float32."""
        self.capacity = capacity
        self.obs = np.zeros((capacity, *observation_shape), np.float32)
        self.next_obs = np.zeros((capacity, *observation_shape), np.float32)
        self.actions = np.zeros((capacity, *action_shape), action_dtype)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self._n = 0
        self._i = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return self._n

    def add(self, obs, action, reward, next_obs, done) -> None:
        i = self._i
        self.obs[i] = obs
        self.actions[i] = action
        self.rewards[i] = reward
        self.next_obs[i] = next_obs
        self.dones[i] = float(done)
        self._i = (i + 1) % self.capacity
        self._n = min(self._n + 1, self.capacity)

    def sample(self, batch_size: int):
        if self._n == 0:
            raise ValueError("replay buffer is empty")
        idx = self._rng.integers(0, self._n, batch_size)
        return (self.obs[idx], self.actions[idx], self.rewards[idx],
                self.next_obs[idx], self.dones[idx])
