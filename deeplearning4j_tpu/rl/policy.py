"""Action-selection policies (↔ org.deeplearning4j.rl4j.policy.{EpsGreedy,
Policy, BoltzmannPolicy-ish ACPolicy sampling})."""

from __future__ import annotations

import numpy as np


class GreedyPolicy:
    def select(self, q_values: np.ndarray, step: int) -> int:
        return int(np.argmax(q_values))


class EpsGreedyPolicy:
    """↔ EpsGreedy: linear anneal from eps_start to eps_min over
    anneal_steps environment steps."""

    def __init__(self, eps_start: float = 1.0, eps_min: float = 0.05,
                 anneal_steps: int = 10_000, seed: int = 0):
        self.eps_start = eps_start
        self.eps_min = eps_min
        self.anneal_steps = anneal_steps
        self._rng = np.random.default_rng(seed)

    def epsilon(self, step: int) -> float:
        frac = min(step / max(self.anneal_steps, 1), 1.0)
        return self.eps_start + (self.eps_min - self.eps_start) * frac

    def select(self, q_values: np.ndarray, step: int) -> int:
        if self._rng.random() < self.epsilon(step):
            return int(self._rng.integers(len(q_values)))
        return int(np.argmax(q_values))


class BoltzmannPolicy:
    """Softmax exploration with temperature."""

    def __init__(self, temperature: float = 1.0, seed: int = 0):
        self.temperature = temperature
        self._rng = np.random.default_rng(seed)

    def select(self, q_values: np.ndarray, step: int) -> int:
        z = q_values / max(self.temperature, 1e-6)
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(q_values), p=p))
