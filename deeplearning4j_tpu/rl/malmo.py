"""Malmo-style mission connector (↔ rl4j-malmo, SURVEY §2.7 RL4J row).

ref: org.deeplearning4j.rl4j.mdp.MalmoEnv + MalmoBox/MalmoActionSpace —
RL4J's Minecraft connector, which adapts a *mission*-driven simulator
(declarative mission spec → episode; pixel-frame observations; discrete
movement commands; per-event rewards) onto its MDP interface. Malmo itself
is an external Minecraft mod that cannot run here (zero egress, no JVM
game process); as with the ALE connector (`rl/history.py`), the deliverable
is the connector half:

- ``MissionSpec``: the declarative episode description the reference
  expresses as mission XML — grid layout, start/goal, hazard blocks,
  reward table, time limit — with JSON round-trip so missions are data,
  not code (the framework-wide config-as-data rule, SURVEY §5.6).
- ``MalmoStyleEnv``: executes a MissionSpec as an MDP with **rendered RGB
  frame observations** ([H, W, 3] uint8, like Malmo's video producer) and
  the discrete movement action set (movenorth/south/east/west). Plugs
  straight into ``HistoryProcessor``/``FrameStackEnv`` and the DQN/A2C
  learners, exactly where the reference's MalmoEnv sat.

A real Malmo endpoint would implement the same two methods against the
game socket; every downstream component is exercised by the synthetic
executor below.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

# Block palette: mission grids are lists of strings over these characters.
_BLOCK_COLORS: Dict[str, Tuple[int, int, int]] = {
    ".": (60, 60, 60),     # floor (stone)
    "#": (120, 85, 40),    # wall (impassable)
    "L": (220, 80, 0),     # lava (hazard, terminal)
    "G": (40, 200, 60),    # goal (emerald, terminal)
    "S": (60, 60, 60),     # start (rendered as floor)
}
_AGENT_COLOR = (230, 230, 40)

ACTIONS: List[str] = ["movenorth", "movesouth", "movewest", "moveeast"]
_DELTAS = {0: (-1, 0), 1: (1, 0), 2: (0, -1), 3: (0, 1)}


@dataclass
class MissionSpec:
    """Declarative mission (↔ Malmo mission XML, as data).

    ``grid`` rows use the block palette: ``.`` floor, ``#`` wall, ``L``
    lava (terminal, ``hazard_reward``), ``G`` goal (terminal,
    ``goal_reward``), ``S`` start cell (exactly one).
    """

    grid: List[str] = field(default_factory=lambda: [
        "#######",
        "#S..L.#",
        "#.##..#",
        "#...#G#",
        "#######",
    ])
    goal_reward: float = 100.0
    hazard_reward: float = -100.0
    step_reward: float = -1.0
    max_steps: int = 100
    cell_px: int = 4  # rendered pixels per grid cell

    def __post_init__(self):
        widths = {len(r) for r in self.grid}
        if len(widths) != 1:
            raise ValueError("mission grid rows must have equal width")
        unknown = {c for r in self.grid for c in r} - set(_BLOCK_COLORS)
        if unknown:
            raise ValueError(f"unknown mission blocks: {sorted(unknown)}")
        starts = sum(r.count("S") for r in self.grid)
        if starts != 1:
            raise ValueError(f"mission needs exactly one 'S' start, got {starts}")

    @property
    def start(self) -> Tuple[int, int]:
        for i, row in enumerate(self.grid):
            j = row.find("S")
            if j >= 0:
                return (i, j)
        raise AssertionError("validated grid lost its start")

    def to_json(self) -> str:
        return json.dumps({
            "grid": self.grid, "goal_reward": self.goal_reward,
            "hazard_reward": self.hazard_reward,
            "step_reward": self.step_reward, "max_steps": self.max_steps,
            "cell_px": self.cell_px,
        })

    @classmethod
    def from_json(cls, s: str) -> "MissionSpec":
        return cls(**json.loads(s))


class MalmoStyleEnv:
    """Mission-executing MDP with RGB frame observations (↔ MalmoEnv).

    Observations are [H, W, 3] uint8 frames (H = rows * cell_px), the raw
    form the DeepMind pipeline in ``rl/history.py`` consumes; actions are
    indices into ``ACTIONS``. Moving into a wall is a no-op step (Malmo
    semantics: the command executes, the agent stays put, time advances).
    """

    def __init__(self, mission: MissionSpec = None):
        self.mission = mission or MissionSpec()
        g = self.mission.grid
        self.action_count = len(ACTIONS)
        self.action_space_n = len(ACTIONS)
        h = len(g) * self.mission.cell_px
        w = len(g[0]) * self.mission.cell_px
        self.observation_shape = (h, w, 3)
        self._pos = self.mission.start
        self._t = 0

    def _render(self) -> np.ndarray:
        px = self.mission.cell_px
        g = self.mission.grid
        frame = np.zeros((len(g) * px, len(g[0]) * px, 3), np.uint8)
        for i, row in enumerate(g):
            for j, c in enumerate(row):
                frame[i * px:(i + 1) * px, j * px:(j + 1) * px] = \
                    _BLOCK_COLORS[c]
        i, j = self._pos
        frame[i * px:(i + 1) * px, j * px:(j + 1) * px] = _AGENT_COLOR
        return frame

    def reset(self) -> np.ndarray:
        self._pos = self.mission.start
        self._t = 0
        return self._render()

    def step(self, action: int):
        di, dj = _DELTAS[int(action)]
        i, j = self._pos
        ni, nj = i + di, j + dj
        g = self.mission.grid
        if 0 <= ni < len(g) and 0 <= nj < len(g[0]) and g[ni][nj] != "#":
            self._pos = (ni, nj)
        self._t += 1
        block = g[self._pos[0]][self._pos[1]]
        if block == "G":
            return self._render(), self.mission.goal_reward, True, \
                {"truncated": False, "block": "goal"}
        if block == "L":
            return self._render(), self.mission.hazard_reward, True, \
                {"truncated": False, "block": "lava"}
        truncated = self._t >= self.mission.max_steps
        return self._render(), self.mission.step_reward, truncated, \
            {"truncated": truncated, "block": block}
