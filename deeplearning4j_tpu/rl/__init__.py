"""Reinforcement learning (↔ rl4j, SURVEY §2.7).

- mdp: MDP interface + built-in toy environments (CartPole, Corridor)
- replay: experience replay buffer
- policy: epsilon-greedy / greedy / Boltzmann action selection
- qlearning: QLearningDiscrete (DQN, double-DQN, target network)
- a2c: advantage actor-critic (n-step rollouts)
"""

from deeplearning4j_tpu.rl.mdp import MDP, CartPole, Corridor
from deeplearning4j_tpu.rl.replay import ReplayBuffer
from deeplearning4j_tpu.rl.policy import BoltzmannPolicy, EpsGreedyPolicy, GreedyPolicy
from deeplearning4j_tpu.rl.qlearning import QLearningDiscrete, QLearningConfig
from deeplearning4j_tpu.rl.a2c import A2C, A2CConfig

__all__ = [
    "MDP", "CartPole", "Corridor",
    "ReplayBuffer",
    "EpsGreedyPolicy", "GreedyPolicy", "BoltzmannPolicy",
    "QLearningDiscrete", "QLearningConfig",
    "A2C", "A2CConfig",
]
