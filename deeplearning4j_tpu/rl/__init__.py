"""Reinforcement learning (↔ rl4j, SURVEY §2.7).

- mdp: MDP interface + built-in toy environments (CartPole, Corridor)
- replay: experience replay buffer
- policy: epsilon-greedy / greedy / Boltzmann action selection
- qlearning: QLearningDiscrete (DQN, double-DQN, target network)
- a2c: advantage actor-critic (n-step rollouts)
- a3c: batched-worker A3C (the reference's async threads, vectorized)
- td3: twin-delayed DDPG for continuous control
"""

from deeplearning4j_tpu.rl.a2c import A2C, A2CConfig
from deeplearning4j_tpu.rl.history import (
    FrameStackEnv,
    HistoryProcessor,
    SyntheticFrameEnv,
)
from deeplearning4j_tpu.rl.a3c import A3CConfig, A3CDiscrete
from deeplearning4j_tpu.rl.malmo import MalmoStyleEnv, MissionSpec
from deeplearning4j_tpu.rl.mdp import MDP, CartPole, Corridor, Pendulum
from deeplearning4j_tpu.rl.policy import BoltzmannPolicy, EpsGreedyPolicy, GreedyPolicy
from deeplearning4j_tpu.rl.qlearning import QLearningConfig, QLearningDiscrete
from deeplearning4j_tpu.rl.replay import ReplayBuffer
from deeplearning4j_tpu.rl.td3 import TD3, TD3Config

__all__ = [
    "MDP", "CartPole", "Corridor", "Pendulum",
    "ReplayBuffer",
    "EpsGreedyPolicy", "GreedyPolicy", "BoltzmannPolicy",
    "QLearningDiscrete", "QLearningConfig",
    "A2C", "A2CConfig",
    "A3CDiscrete", "A3CConfig",
    "TD3", "TD3Config",
    "MissionSpec", "MalmoStyleEnv",
]
