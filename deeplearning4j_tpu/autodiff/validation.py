"""Gradient checking + op-validation coverage ledger.

ref: org.nd4j.autodiff.validation.{OpValidation, GradCheckUtil} and the DL4J
GradientCheckTests family (SURVEY §4): central finite differences in fp64
against analytic gradients, with a ledger tracking which catalog ops have
gradient-check coverage (the reference's OpValidationSuite "coverage" idea).

TPU note: checks run in float64 on the CPU backend (TPU has no fp64); the
analytic side uses the exact same traced program the compiled path uses, so
passing here validates the XLA program's gradients, not a shadow
implementation.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# -- coverage ledger (↔ OpValidationSuite coverage tracking) ----------------

_VALIDATED_OPS: set = set()


def register_validated(op_name: str) -> None:
    _VALIDATED_OPS.add(op_name)


def validated_ops() -> set:
    return set(_VALIDATED_OPS)


def coverage_report() -> Dict[str, Any]:
    from deeplearning4j_tpu.autodiff.samediff import OP_REGISTRY

    all_ops = set(OP_REGISTRY)
    done = _VALIDATED_OPS & all_ops
    return {
        "total_ops": len(all_ops),
        "validated": len(done),
        "fraction": len(done) / max(len(all_ops), 1),
        "missing": sorted(all_ops - done),
    }


@contextlib.contextmanager
def _x64():
    old = jax.config.read("jax_enable_x64")
    jax.config.update("jax_enable_x64", True)
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", old)


def check_gradients(
    fn: Callable[[Dict[str, jnp.ndarray]], jnp.ndarray],
    params: Dict[str, np.ndarray],
    *,
    eps: float = 1e-5,
    max_rel_error: float = 1e-4,
    min_abs_error: float = 1e-8,
    samples_per_param: Optional[int] = 64,
    seed: int = 0,
    op_name: Optional[str] = None,
) -> Dict[str, Any]:
    """Central-difference check of ``grad(fn)`` (↔ GradCheckUtil.checkGradients).

    fn: params dict -> scalar loss (pure, jax-traceable).
    samples_per_param: indices sampled per parameter tensor (None = all —
    the reference checks every element; sampling keeps suites fast).

    Returns a report dict; raises AssertionError on failure.
    """
    with _x64():
        params64 = {k: np.asarray(v, np.float64) for k, v in params.items()}
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            analytic = jax.grad(fn)({k: jnp.asarray(v) for k, v in params64.items()})
            analytic = {k: np.asarray(v) for k, v in analytic.items()}
            # one compiled probe program instead of re-tracing the whole
            # graph eagerly per finite-difference sample
            jit_fn = jax.jit(fn)

            def eval_loss(p):
                return float(jit_fn({k: jnp.asarray(v) for k, v in p.items()}))

            rng = np.random.RandomState(seed)
            worst = {"rel_error": 0.0, "param": None, "index": None}
            checked = 0
            for name, value in params64.items():
                flat = value.reshape(-1)
                n = flat.size
                idxs = (np.arange(n) if samples_per_param is None or n <= samples_per_param
                        else rng.choice(n, samples_per_param, replace=False))
                for i in idxs:
                    orig = flat[i]
                    flat[i] = orig + eps
                    plus = eval_loss(params64)
                    flat[i] = orig - eps
                    minus = eval_loss(params64)
                    flat[i] = orig
                    numeric = (plus - minus) / (2 * eps)
                    a = analytic[name].reshape(-1)[i]
                    denom = max(abs(numeric), abs(a))
                    err = 0.0 if denom == 0 else abs(numeric - a) / denom
                    if abs(numeric - a) < min_abs_error:
                        err = 0.0
                    checked += 1
                    if err > worst["rel_error"]:
                        worst = {"rel_error": err, "param": name, "index": int(i),
                                 "numeric": float(numeric), "analytic": float(a)}

    report = {"checked": checked, "worst": worst, "passed": worst["rel_error"] <= max_rel_error}
    if not report["passed"]:
        raise AssertionError(
            f"gradient check failed: worst rel err {worst['rel_error']:.3e} at "
            f"{worst['param']}[{worst['index']}] "
            f"(numeric {worst.get('numeric')}, analytic {worst.get('analytic')})")
    if op_name:
        register_validated(op_name)
    return report


def check_samediff_gradients(sd, feeds: Dict[str, Any], loss: str,
                             wrt: Optional[Sequence[str]] = None, **kw) -> Dict[str, Any]:
    """Gradient-check a SameDiff graph's loss w.r.t. its VARIABLEs."""
    variables, constants, _ = sd._split_feeds({})
    wrt = list(wrt) if wrt is not None else sorted(variables)
    ph_names = tuple(sorted(feeds))
    fn = sd._build_fn((loss,), ph_names)
    # keep feeds as host numpy so they convert on the CPU fp64 device inside
    # the checker's context (a TPU-committed fp32 array would not).
    feeds_np = {k: np.asarray(v) for k, v in feeds.items()}

    def loss_of(p):
        merged = dict(variables)
        merged.update(p)
        merged = {k: jnp.asarray(v) for k, v in merged.items()}
        ph = {k: jnp.asarray(v) for k, v in feeds_np.items()}
        return fn(merged, constants, ph)[loss]

    return check_gradients(loss_of, {n: variables[n] for n in wrt}, **kw)
