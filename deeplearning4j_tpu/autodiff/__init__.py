"""Graph autodiff layer (↔ SameDiff, SURVEY §2.3).

- samediff: define-by-run graph building, whole-graph XLA compile, grad,
  training, save/load, StableHLO export.
- validation: finite-difference gradient checking + op coverage ledger
  (↔ OpValidation/GradCheckUtil).
"""

from deeplearning4j_tpu.autodiff.samediff import (
    OP_REGISTRY,
    OpNode,
    SameDiff,
    SDVariable,
    TrainingConfig,
    VariableType,
    register_op,
)
from deeplearning4j_tpu.autodiff.validation import (
    check_gradients,
    check_samediff_gradients,
    coverage_report,
    register_validated,
)

__all__ = [
    "SameDiff",
    "SDVariable",
    "VariableType",
    "TrainingConfig",
    "OpNode",
    "OP_REGISTRY",
    "register_op",
    "check_gradients",
    "check_samediff_gradients",
    "coverage_report",
    "register_validated",
]
