"""Subpackage."""
