"""SameDiff-analogue graph program layer — define-by-run graph, compiled whole.

ref: org.nd4j.autodiff.samediff.SameDiff (the ~12k-LoC graph builder god
class), SDVariable, the SD op namespaces (SDMath/SDNN/SDCNN/SDRNN/SDLoss),
AbstractSession/InferenceSession/TrainingSession (topological per-op
interpreters), SameDiff.createGradFunction, SameDiff.save/load (FlatBuffers
.fb), TrainingConfig (SURVEY §2.3, §3.2).

TPU-first inversion: the reference *interprets* the graph op-by-op, paying
JNI dispatch + dependency tracking + refcounting per op per batch. Here the
recorded graph is *replayed once inside jax tracing* and compiled by XLA to
a single TPU program; the per-op interpreter disappears (one dispatch per
step, fusion across the whole graph). An interpreted eager mode is kept for
debugging/listeners (``sd.output(..., interpreted=True)``) — the moral
equivalent of InferenceSession, useful for per-op inspection, never for the
hot path.

Variable taxonomy matches the reference: VARIABLE (trainable, persisted),
CONSTANT (persisted, not trained), PLACEHOLDER (fed per call), ARRAY
(activations — here just recorded graph nodes, never materialized except
under the interpreter).

Serialization: the reference stores graph+weights+updater state in one
FlatBuffers file; here ``save()`` writes a zip of ``graph.json`` (ops,
variables, attrs) + ``arrays.npz`` (VARIABLE/CONSTANT values) + optional
updater state, and ``export_stablehlo()`` additionally serializes the
compiled program itself (jax.export) — the analogue of shipping the
FlatBuffers graph to the native graph executor.
"""

from __future__ import annotations

import dataclasses
import enum
import io
import json
import math
import zipfile
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops import cnn as ops_cnn
from deeplearning4j_tpu.ops import loss as ops_loss
from deeplearning4j_tpu.ops import math as ops_math
from deeplearning4j_tpu.ops import nn as ops_nn
from deeplearning4j_tpu.ops import rnn as ops_rnn

# ---------------------------------------------------------------------------
# Op registry: op-name -> pure callable. Ops must be registered by name so
# graphs are serializable (↔ libnd4j OpRegistrator / DifferentialFunction
# opName()). kwargs recorded in the graph must be JSON-able.
# ---------------------------------------------------------------------------

OP_REGISTRY: Dict[str, Callable] = {}


def register_op(name: str, fn: Callable) -> None:
    OP_REGISTRY[name] = fn


def _register_module(prefix: str, module, names: Optional[Sequence[str]] = None):
    for attr in names if names is not None else dir(module):
        if attr.startswith("_"):
            continue
        fn = getattr(module, attr, None)
        if callable(fn):
            register_op(f"{prefix}.{attr}", fn)


_register_module("math", ops_math)
_register_module("nn", ops_nn)
_register_module("cnn", ops_cnn)
_register_module("rnn", ops_rnn)
_register_module("loss", ops_loss)

# Core structural ops (↔ the reference's SDBaseOps on SameDiff itself).
_CORE_OPS = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "pow": jnp.power,
    "mod": jnp.mod,
    "neg": jnp.negative,
    "matmul": lambda a, b: jnp.matmul(a, b),
    "reshape": lambda x, shape: jnp.reshape(x, shape),
    "transpose": lambda x, axes=None: jnp.transpose(x, axes),
    "permute": lambda x, axes: jnp.transpose(x, axes),
    "expand_dims": lambda x, axis: jnp.expand_dims(x, axis),
    "squeeze": lambda x, axis=None: jnp.squeeze(x, axis),
    "concat": lambda *xs, axis=0: jnp.concatenate(xs, axis=axis),
    "stack": lambda *xs, axis=0: jnp.stack(xs, axis=axis),
    "unstack": lambda x, axis=0: tuple(jnp.moveaxis(x, axis, 0)),
    "slice": lambda x, begin, size: jax.lax.dynamic_slice(x, begin, size),
    "strided_slice": lambda x, begin, end, strides: x[
        tuple(slice(b, e, s) for b, e, s in zip(begin, end, strides))
    ],
    "gather": lambda x, indices, axis=0: jnp.take(x, jnp.asarray(indices), axis=axis),
    "tile": lambda x, reps: jnp.tile(x, reps),
    "cast": lambda x, dtype: x.astype(jnp.dtype(dtype)),
    "sum": lambda x, axis=None, keepdims=False: jnp.sum(x, axis=_ax(axis), keepdims=keepdims),
    "mean": lambda x, axis=None, keepdims=False: jnp.mean(x, axis=_ax(axis), keepdims=keepdims),
    "max": lambda x, axis=None, keepdims=False: jnp.max(x, axis=_ax(axis), keepdims=keepdims),
    "min": lambda x, axis=None, keepdims=False: jnp.min(x, axis=_ax(axis), keepdims=keepdims),
    "prod": lambda x, axis=None, keepdims=False: jnp.prod(x, axis=_ax(axis), keepdims=keepdims),
    "std": lambda x, axis=None, keepdims=False, bias_corrected=True: jnp.std(
        x, axis=_ax(axis), keepdims=keepdims, ddof=1 if bias_corrected else 0
    ),
    "var": lambda x, axis=None, keepdims=False, bias_corrected=True: jnp.var(
        x, axis=_ax(axis), keepdims=keepdims, ddof=1 if bias_corrected else 0
    ),
    "argmax": lambda x, axis=None: jnp.argmax(x, axis=axis),
    "argmin": lambda x, axis=None: jnp.argmin(x, axis=axis),
    "softmax": lambda x, axis=-1: jax.nn.softmax(x, axis=axis),
    "log_softmax": lambda x, axis=-1: jax.nn.log_softmax(x, axis=axis),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "leaky_relu": lambda x, alpha=0.01: jax.nn.leaky_relu(x, alpha),
    "elu": jax.nn.elu,
    "gelu": jax.nn.gelu,
    "selu": jax.nn.selu,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "swish": jax.nn.swish,
    "exp": jnp.exp,
    "log": jnp.log,
    "sqrt": jnp.sqrt,
    "square": jnp.square,
    "abs": jnp.abs,
    "eq": lambda a, b: jnp.equal(a, b),
    "neq": lambda a, b: jnp.not_equal(a, b),
    "gt": lambda a, b: jnp.greater(a, b),
    "gte": lambda a, b: jnp.greater_equal(a, b),
    "lt": lambda a, b: jnp.less(a, b),
    "lte": lambda a, b: jnp.less_equal(a, b),
    "where": lambda c, a, b: jnp.where(c, a, b),
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "cumsum": lambda x, axis=0: jnp.cumsum(x, axis=axis),
    "cumprod": lambda x, axis=0: jnp.cumprod(x, axis=axis),
    "zeros_like": jnp.zeros_like,
    "ones_like": jnp.ones_like,
    "identity": lambda x: x,
    "shape_of": lambda x: jnp.asarray(x.shape, jnp.int32),
    "size": lambda x: jnp.asarray(x.size, jnp.int32),
    "rank": lambda x: jnp.asarray(x.ndim, jnp.int32),
}


def _ax(axis):
    return tuple(axis) if isinstance(axis, list) else axis


for _n, _f in _CORE_OPS.items():
    register_op(_n, _f)


class VariableType(str, enum.Enum):
    """ref: org.nd4j.autodiff.samediff.VariableType."""

    VARIABLE = "VARIABLE"
    CONSTANT = "CONSTANT"
    PLACEHOLDER = "PLACEHOLDER"
    ARRAY = "ARRAY"


@dataclasses.dataclass
class OpNode:
    """One recorded graph op (↔ SameDiffOp: op + input/output var names)."""

    op: str
    inputs: List[str]
    outputs: List[str]
    attrs: Dict[str, Any]
    subgraphs: Optional[Dict[str, "SameDiff"]] = None  # control flow branches


class SDVariable:
    """Symbolic handle into a SameDiff graph (↔ org.nd4j.autodiff.samediff.SDVariable)."""

    def __init__(self, sd: "SameDiff", name: str, var_type: VariableType,
                 shape=None, dtype=None):
        self.sd = sd
        self.name = name
        self.var_type = var_type
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = str(np.dtype(dtype)) if dtype is not None else None

    # -- arithmetic sugar (↔ SDVariable.add/sub/mul/... and rsub/rdiv) -----
    def _bin(self, op, other, reverse=False):
        other = self.sd._lift(other)
        a, b = (other, self) if reverse else (self, other)
        return self.sd._record(op, [a, b], {})

    def __add__(self, o):
        return self._bin("add", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._bin("sub", o)

    def __rsub__(self, o):
        return self._bin("sub", o, reverse=True)

    def __mul__(self, o):
        return self._bin("mul", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._bin("div", o)

    def __rtruediv__(self, o):
        return self._bin("div", o, reverse=True)

    def __pow__(self, o):
        return self._bin("pow", o)

    def __matmul__(self, o):
        return self._bin("matmul", o)

    def __neg__(self):
        return self.sd._record("neg", [self], {})

    # DL4J method names
    def add(self, o):
        return self + o

    def sub(self, o):
        return self - o

    def mul(self, o):
        return self * o

    def div(self, o):
        return self / o

    def rsub(self, o):
        return self._bin("sub", o, reverse=True)

    def rdiv(self, o):
        return self._bin("div", o, reverse=True)

    def mmul(self, o):
        return self @ o

    def dot(self, o):
        return self @ o

    # comparisons
    def eq(self, o):
        return self._bin("eq", o)

    def neq(self, o):
        return self._bin("neq", o)

    def gt(self, o):
        return self._bin("gt", o)

    def gte(self, o):
        return self._bin("gte", o)

    def lt(self, o):
        return self._bin("lt", o)

    def lte(self, o):
        return self._bin("lte", o)

    # shape ops
    def reshape(self, *shape):
        shape = shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape
        return self.sd._record("reshape", [self], {"shape": list(shape)})

    def transpose(self, axes=None):
        return self.sd._record("transpose", [self], {"axes": list(axes) if axes else None})

    def permute(self, *axes):
        axes = axes[0] if len(axes) == 1 and isinstance(axes[0], (tuple, list)) else axes
        return self.sd._record("permute", [self], {"axes": list(axes)})

    def cast(self, dtype):
        return self.sd._record("cast", [self], {"dtype": str(np.dtype(dtype))})

    # reductions
    def sum(self, axis=None, keepdims=False):
        return self.sd._record("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return self.sd._record("mean", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return self.sd._record("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return self.sd._record("min", [self], {"axis": axis, "keepdims": keepdims})

    def std(self, bias_corrected=True, axis=None, keepdims=False):
        return self.sd._record(
            "std", [self],
            {"axis": axis, "keepdims": keepdims, "bias_corrected": bias_corrected})

    def norm2(self, axis=None):
        return self.sd._record("math.norm2", [self], {"axis": axis})

    def argmax(self, axis=None):
        return self.sd._record("argmax", [self], {"axis": axis})

    def argmin(self, axis=None):
        return self.sd._record("argmin", [self], {"axis": axis})

    # evaluation
    def eval(self, feeds: Optional[Dict[str, Any]] = None):
        """Evaluate this variable (↔ SDVariable.eval())."""
        return self.sd.output(feeds or {}, [self.name])[self.name]

    def __repr__(self):
        return (f"SDVariable(name={self.name!r}, type={self.var_type.value}, "
                f"shape={self.shape}, dtype={self.dtype})")


class _Namespace:
    """Recording wrapper over one ops module (↔ SDMath/SDNN/SDCNN/SDRNN/SDLoss)."""

    def __init__(self, sd: "SameDiff", prefix: str):
        self._sd = sd
        self._prefix = prefix

    def __getattr__(self, opname: str):
        full = f"{self._prefix}.{opname}"
        if full not in OP_REGISTRY:
            raise AttributeError(f"no op {full!r} in registry")
        sd = self._sd

        def record(*args, **kwargs):
            var_args = [sd._lift(a) if _is_arrayish(a) or isinstance(a, SDVariable) else a
                        for a in args]
            inputs = [a for a in var_args if isinstance(a, SDVariable)]
            # Non-variable positional args (ints, tuples...) become attrs by
            # position; the replay reconstructs the original arg order.
            arg_kinds = ["var" if isinstance(a, SDVariable) else "attr" for a in var_args]
            attr_pos = [a for a in var_args if not isinstance(a, SDVariable)]
            attrs = dict(kwargs)
            attrs["__argspec__"] = arg_kinds
            attrs["__posattrs__"] = attr_pos
            return sd._record(full, inputs, attrs)

        return record


def _is_arrayish(a) -> bool:
    # Python scalars stay attrs (serializable); arrays become constants.
    return isinstance(a, (np.ndarray, jax.Array))


def _replay_call(fn, node: OpNode, input_vals: List[Any]):
    attrs = dict(node.attrs)
    argspec = attrs.pop("__argspec__", None)
    posattrs = list(attrs.pop("__posattrs__", []))
    if argspec is None:
        return fn(*input_vals, **_dejson(attrs))
    args = []
    vi = iter(input_vals)
    ai = iter(posattrs)
    for kind in argspec:
        args.append(next(vi) if kind == "var" else _dejson_val(next(ai)))
    return fn(*args, **_dejson(attrs))


def _dejson(attrs: Dict[str, Any]) -> Dict[str, Any]:
    return {k: _dejson_val(v) for k, v in attrs.items()}


def _dejson_val(v):
    if isinstance(v, list):
        return tuple(_dejson_val(x) for x in v)
    return v


class SameDiff:
    """The graph builder + executor (↔ org.nd4j.autodiff.samediff.SameDiff).

    Usage mirrors the reference::

        sd = SameDiff.create()
        x = sd.placeholder("x", (None, 784), "float32")
        w = sd.var("w", np.zeros((784, 10), np.float32))
        b = sd.var("b", np.zeros((10,), np.float32))
        logits = x.mmul(w) + b
        probs = sd.nn.softmax(logits)  # recorded op
        out = probs.eval({"x": batch})
    """

    def __init__(self):
        self._vars: Dict[str, SDVariable] = {}
        self._values: Dict[str, np.ndarray] = {}  # VARIABLE + CONSTANT data
        self._nodes: List[OpNode] = []
        self._producer: Dict[str, int] = {}  # var name -> node index
        self._counter = 0
        self._fn_cache: Dict[Tuple, Callable] = {}
        self.math = _Namespace(self, "math")
        self.nn = _Namespace(self, "nn")
        self.cnn = _Namespace(self, "cnn")
        self.rnn = _Namespace(self, "rnn")
        self.loss = _Namespace(self, "loss")
        self.training_config: Optional[TrainingConfig] = None
        self._updater_state = None
        self._updater_leaves = None  # loaded-from-checkpoint leaves, pending restore
        self._iteration = 0
        self.listeners: List[Any] = []
        # When this graph is a control-flow branch (cond/while subgraph),
        # an explicit ordered output list. None = the terminal-vars
        # heuristic in _as_branch_fn. The TF importer sets this: a
        # FunctionDef's rets are named and ordered, and loop-carry order
        # must match lax.while_loop's carry exactly.
        self.branch_outputs: Optional[List[str]] = None

    # -- construction ------------------------------------------------------

    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    def _fresh_name(self, base: str) -> str:
        self._counter += 1
        name = f"{base}_{self._counter}"
        while name in self._vars:
            self._counter += 1
            name = f"{base}_{self._counter}"
        return name

    def _add_var(self, name, var_type, shape=None, dtype=None) -> SDVariable:
        if name in self._vars:
            raise ValueError(f"variable {name!r} already exists")
        v = SDVariable(self, name, var_type, shape, dtype)
        self._vars[name] = v
        return v

    def var(self, name: str, value=None, shape=None, dtype="float32",
            initializer=None, seed: int = 0) -> SDVariable:
        """Trainable VARIABLE (↔ sd.var). Give ``value`` or ``shape``+init."""
        if value is None:
            if shape is None:
                raise ValueError("var needs value or shape")
            if initializer is None:
                value = np.zeros(shape, dtype)
            else:
                from deeplearning4j_tpu.nn.initializers import get_initializer
                init = get_initializer(initializer)
                value = np.asarray(
                    init(jax.random.key(seed), tuple(shape), jnp.dtype(dtype)))
        value = np.asarray(value)
        v = self._add_var(name, VariableType.VARIABLE, value.shape, value.dtype)
        self._values[name] = value
        return v

    def constant(self, name: str, value) -> SDVariable:
        value = np.asarray(value)
        v = self._add_var(name, VariableType.CONSTANT, value.shape, value.dtype)
        self._values[name] = value
        return v

    def placeholder(self, name: str, shape=None, dtype="float32") -> SDVariable:
        return self._add_var(name, VariableType.PLACEHOLDER, shape, dtype)

    def convert_to_variable(self, name: str) -> SDVariable:
        """CONSTANT → trainable VARIABLE in place (↔ sd.convertToVariable).

        The model-import path creates weights as constants; fine-tuning an
        imported graph promotes them so gradients/updaters apply.
        """
        v = self._vars[name]
        if v.var_type == VariableType.VARIABLE:
            return v
        if v.var_type != VariableType.CONSTANT:
            raise ValueError(f"{name!r} is {v.var_type.value}, not constant")
        v.var_type = VariableType.VARIABLE
        self._fn_cache.clear()
        # Updater state is keyed to the trainable set; a stale pytree would
        # mismatch on the next fit().
        self._updater_state = None
        self._updater_leaves = None
        return v

    def convert_to_constant(self, name: str) -> SDVariable:
        """VARIABLE → CONSTANT in place (↔ sd.convertToConstant) — e.g.
        freezing layers before fine-tuning."""
        v = self._vars[name]
        if v.var_type == VariableType.CONSTANT:
            return v
        if v.var_type != VariableType.VARIABLE:
            raise ValueError(f"{name!r} is {v.var_type.value}, not variable")
        v.var_type = VariableType.CONSTANT
        self._fn_cache.clear()
        self._updater_state = None
        self._updater_leaves = None
        return v

    def _lift(self, value) -> SDVariable:
        """Wrap a literal array/scalar as an (anonymous) constant variable."""
        if isinstance(value, SDVariable):
            return value
        arr = np.asarray(value)
        name = self._fresh_name("const")
        v = self._add_var(name, VariableType.CONSTANT, arr.shape, arr.dtype)
        self._values[name] = arr
        return v

    # -- recording ---------------------------------------------------------

    def _record(self, op: str, inputs: List[SDVariable], attrs: Dict[str, Any],
                subgraphs: Optional[Dict[str, "SameDiff"]] = None):
        if op not in OP_REGISTRY:
            raise KeyError(f"op {op!r} not registered")
        for v in inputs:
            if v.sd is not self:
                raise ValueError(f"variable {v.name} belongs to another graph")
        out_structs = self._infer(op, inputs, attrs, subgraphs)
        base = op.split(".")[-1]
        outs: List[SDVariable] = []
        for s in out_structs:
            name = self._fresh_name(base)
            shape = getattr(s, "shape", None)
            dtype = getattr(s, "dtype", None)
            outs.append(self._add_var(name, VariableType.ARRAY, shape, dtype))
        node = OpNode(op, [v.name for v in inputs], [v.name for v in outs],
                      _jsonable_attrs(attrs), subgraphs)
        idx = len(self._nodes)
        self._nodes.append(node)
        for v in outs:
            self._producer[v.name] = idx
        self._fn_cache.clear()
        return outs[0] if len(outs) == 1 else tuple(outs)

    def _infer(self, op, inputs, attrs, subgraphs):
        """Shape/dtype inference via abstract eval (↔ libnd4j shape functions)."""
        fn = OP_REGISTRY[op]
        structs = []
        for v in inputs:
            shape = tuple(2 if (d is None or d == -1) else d for d in (v.shape or ()))
            dtype = v.dtype or "float32"
            structs.append(jax.ShapeDtypeStruct(shape, jnp.dtype(dtype)))
        node = OpNode(op, [v.name for v in inputs], [], dict(attrs), subgraphs)
        try:
            out = jax.eval_shape(
                lambda *vals: _replay_call_node(self, node, fn, list(vals)), *structs)
        except Exception as e:
            if op in ("__while__", "__cond__"):
                # Control flow MUST infer: its output arity equals the
                # carry/branch arity, and a silent single-unknown fallback
                # would mis-wire every downstream consumer (carry dtype
                # mismatches surface here, e.g. lax.while_loop rejecting
                # an inconsistent body).
                raise ValueError(
                    f"control-flow op {op} failed shape inference: "
                    f"{e}") from e
            return [_UnknownStruct()]
        leaves = out if isinstance(out, (tuple, list)) else [out]
        sym = any(v.shape is not None and any(d in (None, -1) for d in v.shape)
                  for v in inputs)
        if sym:
            # dims were substituted; keep rank/dtype, drop dim values we faked
            return [_UnknownStruct(getattr(s, "dtype", None)) for s in leaves]
        return list(leaves)

    # -- execution ---------------------------------------------------------

    def _ancestors(self, names: Sequence[str]) -> List[int]:
        """Node indices needed to compute `names`, in topological order."""
        needed: set = set()
        stack = [n for n in names if n in self._producer]
        while stack:
            vn = stack.pop()
            idx = self._producer.get(vn)
            if idx is None or idx in needed:
                continue
            needed.add(idx)
            stack.extend(self._nodes[idx].inputs)
        return sorted(needed)

    def _build_fn(self, output_names: Tuple[str, ...], placeholder_names: Tuple[str, ...]):
        """Pure fn(variables, constants, placeholders) -> outputs: replays the
        recorded graph inside jax tracing — compiled ONCE by XLA."""
        order = self._ancestors(output_names)
        nodes = [self._nodes[i] for i in order]

        def fn(variables, constants, placeholders):
            env: Dict[str, Any] = {}
            env.update(constants)
            env.update(variables)
            env.update(placeholders)
            for node in nodes:
                f = OP_REGISTRY[node.op]
                vals = [env[n] for n in node.inputs]
                out = _replay_call_node(self, node, f, vals)
                if isinstance(out, (tuple, list)):
                    for n, o in zip(node.outputs, out):
                        env[n] = o
                else:
                    env[node.outputs[0]] = out
            missing = [n for n in output_names if n not in env]
            if missing:
                raise KeyError(f"outputs not computable: {missing}")
            return {n: env[n] for n in output_names}

        return fn

    def _split_feeds(self, feeds: Dict[str, Any]):
        placeholders = {}
        for k, v in feeds.items():
            if k not in self._vars:
                raise KeyError(f"unknown placeholder {k!r}")
            vt = self._vars[k].var_type
            if vt != VariableType.PLACEHOLDER:
                # Feeding a VARIABLE/CONSTANT would silently shadow its
                # stored value (r1 advisor); state changes go through
                # set_value / convert_to_variable instead.
                raise ValueError(
                    f"cannot feed {vt.name} {k!r}: only placeholders accept "
                    "feeds (use set_value to change stored values)")
            placeholders[k] = jnp.asarray(v)
        variables = {n: self._values[n] for n, v in self._vars.items()
                     if v.var_type == VariableType.VARIABLE}
        constants = {n: self._values[n] for n, v in self._vars.items()
                     if v.var_type == VariableType.CONSTANT}
        return variables, constants, placeholders

    def output(self, feeds: Dict[str, Any], outputs: Sequence[str],
               interpreted: bool = False) -> Dict[str, Any]:
        """Run the graph (↔ SameDiff.output / InferenceSession).

        Compiled by default (whole-graph XLA). ``interpreted=True`` replays
        op-by-op eagerly — the InferenceSession analogue for debugging; op
        listeners (``listeners`` with ``on_op(node, outputs)``) fire only in
        this mode, since compiled execution has no per-op host boundary.
        """
        outputs = tuple(outputs)
        variables, constants, placeholders = self._split_feeds(feeds)
        if interpreted:
            return self._interpret(variables, constants, placeholders, outputs)
        key = (outputs, tuple(sorted(placeholders)))
        if key not in self._fn_cache:
            fn = self._build_fn(outputs, tuple(sorted(placeholders)))
            self._fn_cache[key] = jax.jit(fn)
        res = self._fn_cache[key](variables, constants, placeholders)
        return {k: np.asarray(v) for k, v in res.items()}

    def _interpret(self, variables, constants, placeholders, outputs):
        env = {**constants, **variables, **placeholders}
        for idx in self._ancestors(outputs):
            node = self._nodes[idx]
            f = OP_REGISTRY[node.op]
            out = _replay_call_node(self, node, f, [env[n] for n in node.inputs])
            outs = out if isinstance(out, (tuple, list)) else [out]
            for n, o in zip(node.outputs, outs):
                env[n] = o
            for lst in self.listeners:
                if hasattr(lst, "on_op"):
                    lst.on_op(node, {n: env[n] for n in node.outputs})
        return {n: np.asarray(env[n]) for n in outputs}

    def batch_output(self, feeds, outputs):
        return self.output(feeds, outputs)

    # -- gradients (↔ SameDiff.createGradFunction / calculateGradients) ----

    def calculate_gradients(self, feeds: Dict[str, Any], loss: str,
                            wrt: Optional[Sequence[str]] = None) -> Dict[str, np.ndarray]:
        """Gradients of scalar `loss` w.r.t. VARIABLEs (default: all).

        The reference builds a reverse-mode grad *sub-graph* lazily via
        per-op doDiff; here jax.grad derives it from the same replayed
        trace and XLA compiles forward+backward as one program.
        """
        variables, constants, placeholders = self._split_feeds(feeds)
        wrt = tuple(wrt) if wrt is not None else tuple(sorted(variables))
        fn = self._build_fn((loss,), tuple(sorted(placeholders)))

        def loss_of(wrt_vals):
            merged = dict(variables)
            merged.update(wrt_vals)
            out = fn(merged, constants, placeholders)[loss]
            if out.ndim != 0:
                raise ValueError(f"loss {loss!r} is not scalar: shape {out.shape}")
            return out

        grads = jax.jit(jax.grad(loss_of))({n: variables[n] for n in wrt})
        return {k: np.asarray(v) for k, v in grads.items()}

    def grad(self, feeds, loss, var_name):
        return self.calculate_gradients(feeds, loss, [var_name])[var_name]

    # -- control flow (↔ sd.ifCond / sd.whileLoop; lax.cond / while_loop) --

    def cond(self, pred: SDVariable, true_graph: "SameDiff", false_graph: "SameDiff",
             inputs: Sequence[SDVariable]):
        """Record an If: branch subgraphs map their placeholders (declared
        order) to `inputs`. ↔ sd.ifCond; compiles to lax.cond (both branches
        traced, one executed — XLA control flow, no host round-trip)."""
        return self._record("__cond__", [pred, *inputs], {},
                            {"true": true_graph, "false": false_graph})

    def while_loop(self, cond_graph: "SameDiff", body_graph: "SameDiff",
                   inits: Sequence[SDVariable]):
        """Record a While: ↔ sd.whileLoop; compiles to lax.while_loop.

        Reverse-mode differentiation through a while_loop is undefined
        (XLA semantics: dynamic trip count, nothing to checkpoint
        against); calculate_gradients over a graph containing one raises.
        Express differentiable loops as scan-style programs (fixed trip
        count) instead."""
        return self._record("__while__", list(inits), {},
                            {"cond": cond_graph, "body": body_graph})

    def _as_branch_fn(self):
        """This graph as fn(*placeholder_values) -> outputs tuple.
        Outputs are ``branch_outputs`` when set (explicit, ordered — may
        include placeholders for pass-through loop vars), else all
        terminal ARRAY vars (no consumer)."""
        ph = [n for n, v in self._vars.items() if v.var_type == VariableType.PLACEHOLDER]
        if self.branch_outputs is not None:
            outs = list(self.branch_outputs)
        else:
            consumed = {n for node in self._nodes for n in node.inputs}
            outs = [n for n, v in self._vars.items()
                    if v.var_type == VariableType.ARRAY and n not in consumed]
        fn = self._build_fn(tuple(outs), tuple(ph))
        variables = {n: self._values[n] for n, v in self._vars.items()
                     if v.var_type == VariableType.VARIABLE}
        constants = {n: self._values[n] for n, v in self._vars.items()
                     if v.var_type == VariableType.CONSTANT}

        def branch(*vals):
            res = fn(variables, constants, dict(zip(ph, vals)))
            out_vals = tuple(res[n] for n in outs)
            return out_vals[0] if len(out_vals) == 1 else out_vals

        return branch

    # -- training (↔ TrainingSession + SameDiff.fit) -----------------------

    def fit(self, data, config: Optional["TrainingConfig"] = None, *,
            epochs: int = 1, listeners: Optional[List] = None):
        """Train the graph's VARIABLEs. `data` yields dict batches mapping
        placeholder names -> arrays."""
        from deeplearning4j_tpu.train.updaters import apply_updates, resolve_updater

        config = config or self.training_config
        if config is None:
            raise ValueError("no TrainingConfig set")
        self.training_config = config
        listeners = listeners or []

        upd_init, upd_update = resolve_updater(config.updater, **config.updater_args).make()
        variables, constants, _ = self._split_feeds({})
        trainable = {n: jnp.asarray(v) for n, v in variables.items()}
        if self._updater_state is not None:
            opt_state = self._updater_state
        else:
            opt_state = upd_init(trainable)
            if self._updater_leaves is not None:
                # restore a loaded checkpoint's optimizer state into the
                # freshly-built state's tree structure
                treedef = jax.tree_util.tree_structure(opt_state)
                opt_state = jax.tree_util.tree_unflatten(treedef, self._updater_leaves)
                self._updater_leaves = None
        ph_names = tuple(sorted(config.placeholders(self)))
        fn = self._build_fn((config.loss_variable,), ph_names)

        def step(params, opt_state, step_i, batch):
            def loss_of(p):
                loss = fn(p, constants, batch)[config.loss_variable]
                if config.l2 > 0:
                    loss = loss + config.l2 * sum(
                        jnp.sum(jnp.square(x)) for x in jax.tree_util.tree_leaves(p))
                if config.l1 > 0:
                    loss = loss + config.l1 * sum(
                        jnp.sum(jnp.abs(x)) for x in jax.tree_util.tree_leaves(p))
                return loss

            loss, grads = jax.value_and_grad(loss_of)(params)
            updates, new_opt = upd_update(grads, opt_state, params, step_i)
            return apply_updates(params, updates), new_opt, loss

        jit_step = jax.jit(step, donate_argnums=(0, 1))
        it_count = self._iteration
        history = []
        for epoch in range(epochs):
            epoch_losses = []
            for batch in data:
                batch = {k: jnp.asarray(v) for k, v in batch.items() if k in ph_names}
                trainable, opt_state, loss = jit_step(
                    trainable, opt_state, jnp.asarray(it_count), batch)
                it_count += 1
                epoch_losses.append(loss)
                for lst in listeners:
                    if hasattr(lst, "on_iteration"):
                        lst.on_iteration(epoch, it_count, None,
                                         {"total_loss": loss})
            if not epoch_losses:
                if epoch == 0:
                    raise ValueError("fit(): data iterable yielded no batches")
                break  # one-shot generator exhausted; don't record stale epochs
            if hasattr(data, "reset"):
                data.reset()
            history.append(float(np.mean(jax.device_get(epoch_losses))))
        for n, v in trainable.items():
            self._values[n] = np.asarray(jax.device_get(v))
        self._updater_state = jax.device_get(opt_state)
        self._iteration = it_count
        return history

    # -- introspection -----------------------------------------------------

    def variables(self) -> List[SDVariable]:
        return list(self._vars.values())

    def get_variable(self, name: str) -> SDVariable:
        return self._vars[name]

    def get_value(self, name: str) -> np.ndarray:
        return self._values[name]

    def set_value(self, name: str, value) -> None:
        if self._vars[name].var_type not in (VariableType.VARIABLE, VariableType.CONSTANT):
            raise ValueError(f"{name} holds no persistent value")
        self._values[name] = np.asarray(value)

    def ops(self) -> List[OpNode]:
        return list(self._nodes)

    def summary(self) -> str:
        lines = [f"SameDiff: {len(self._vars)} vars, {len(self._nodes)} ops"]
        for n, v in self._vars.items():
            if v.var_type != VariableType.ARRAY:
                lines.append(f"  {v.var_type.value:<12} {n:<24} {v.shape} {v.dtype}")
        for node in self._nodes:
            lines.append(f"  op {node.op:<20} {node.inputs} -> {node.outputs}")
        return "\n".join(lines)

    # -- serialization (↔ SameDiff.save/load FlatBuffers .fb) --------------

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "format": "deeplearning4j_tpu.samediff.v1",
            "variables": [
                {"name": n, "type": v.var_type.value, "shape": list(v.shape) if v.shape else None,
                 "dtype": v.dtype}
                for n, v in self._vars.items()
            ],
            "ops": [
                {
                    "op": node.op, "inputs": node.inputs, "outputs": node.outputs,
                    "attrs": node.attrs,
                    "subgraphs": {k: g.to_dict() for k, g in node.subgraphs.items()}
                    if node.subgraphs else None,
                }
                for node in self._nodes
            ],
            "training_config": dataclasses.asdict(self.training_config)
            if self.training_config else None,
            "iteration": self._iteration,
        }
        if self.branch_outputs is not None:
            d["branch_outputs"] = list(self.branch_outputs)
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SameDiff":
        sd = SameDiff()
        for v in d["variables"]:
            sd._vars[v["name"]] = SDVariable(
                sd, v["name"], VariableType(v["type"]), v["shape"], v["dtype"])
        for i, o in enumerate(d["ops"]):
            subgraphs = {k: SameDiff.from_dict(g) for k, g in o["subgraphs"].items()} \
                if o.get("subgraphs") else None
            node = OpNode(o["op"], list(o["inputs"]), list(o["outputs"]),
                          dict(o["attrs"]), subgraphs)
            sd._nodes.append(node)
            for out in node.outputs:
                sd._producer[out] = i
        if d.get("training_config"):
            sd.training_config = TrainingConfig(**d["training_config"])
        sd._iteration = int(d.get("iteration", 0))
        sd._counter = len(sd._vars)
        if d.get("branch_outputs") is not None:
            sd.branch_outputs = list(d["branch_outputs"])
        return sd

    def _collect_subgraph_values(self, prefix: str, out: Dict[str, Any]) -> None:
        """Flatten control-flow subgraph constants into npz-able keys:
        ``__sub__|<node_idx>|<subgraph_key>|...|<var_name>``. Subgraphs
        hold their own _values (loop bounds — or captured weights, for
        functional TF imports), which the top-level npz otherwise never
        sees; npz keeps weight-scale constants binary instead of blowing
        up graph.json as JSON text."""
        for i, node in enumerate(self._nodes):
            if not node.subgraphs:
                continue
            for k, g in node.subgraphs.items():
                p = f"{prefix}{i}|{k}|"
                for n, v in g._values.items():
                    if "|" in n:
                        raise ValueError(
                            f"subgraph variable name {n!r} contains '|'")
                    out[f"__sub__|{p}{n}"] = np.asarray(v)
                g._collect_subgraph_values(p, out)

    def _inject_subgraph_value(self, key: str, value) -> None:
        tokens = key.split("|")
        g = self
        while len(tokens) > 1:
            g = g._nodes[int(tokens[0])].subgraphs[tokens[1]]
            tokens = tokens[2:]
        g._values[tokens[0]] = value

    def save(self, path, save_updater_state: bool = True) -> None:
        """One-file zip: graph.json + arrays.npz (+ updater npz)."""
        sub_vals: Dict[str, Any] = {}
        self._collect_subgraph_values("", sub_vals)
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("graph.json", json.dumps(self.to_dict(), indent=1))
            buf = io.BytesIO()
            np.savez(buf, **self._values, **sub_vals)
            zf.writestr("arrays.npz", buf.getvalue())
            if save_updater_state and self._updater_state is not None:
                leaves, treedef = jax.tree_util.tree_flatten(self._updater_state)
                ubuf = io.BytesIO()
                np.savez(ubuf, **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)})
                zf.writestr("updater.npz", ubuf.getvalue())

    @staticmethod
    def load(path) -> "SameDiff":
        with zipfile.ZipFile(path, "r") as zf:
            sd = SameDiff.from_dict(json.loads(zf.read("graph.json")))
            with np.load(io.BytesIO(zf.read("arrays.npz"))) as npz:
                sd._values = {k: npz[k] for k in npz.files
                              if not k.startswith("__sub__|")}
                for k in npz.files:
                    if k.startswith("__sub__|"):
                        sd._inject_subgraph_value(
                            k[len("__sub__|"):], npz[k])
            if "updater.npz" in zf.namelist():
                with np.load(io.BytesIO(zf.read("updater.npz"))) as unpz:
                    sd._updater_leaves = [
                        unpz[f"leaf_{i}"] for i in range(len(unpz.files))]
        return sd

    # -- StableHLO export (↔ shipping the .fb graph to the native executor) -

    def export_stablehlo(self, outputs: Sequence[str],
                         feed_specs: Dict[str, Tuple[Tuple[int, ...], str]]) -> bytes:
        """Serialize the compiled program (jax.export). feed_specs maps
        placeholder name -> (shape, dtype). The result runs anywhere PJRT
        does — the role libnd4j's FlatBuffers GraphExecutioner played."""
        from jax import export as jexport

        outputs = tuple(outputs)
        self._require_placeholders(feed_specs)
        ph_names = tuple(sorted(feed_specs))
        fn = self._build_fn(outputs, ph_names)
        variables, constants, _ = self._split_feeds({})

        def program(placeholders):
            return fn(variables, constants, placeholders)

        specs = {n: jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
                 for n, (s, d) in feed_specs.items()}
        return bytes(jexport.export(jax.jit(program))(specs).serialize())

    def _require_placeholders(self, names) -> None:
        """Exported-program inputs must be PLACEHOLDERs: a VARIABLE or
        CONSTANT name here would silently become a runtime input shadowing
        its stored value (same hazard _split_feeds rejects for feeds)."""
        for n in names:
            if n not in self._vars:
                raise KeyError(f"unknown placeholder {n!r}")
            vt = self._vars[n].var_type
            if vt != VariableType.PLACEHOLDER:
                raise ValueError(
                    f"export input {n!r} is {vt.name}, not a placeholder")

    @staticmethod
    def run_stablehlo(blob: bytes, feeds: Dict[str, Any]) -> Dict[str, np.ndarray]:
        from jax import export as jexport

        fn = jexport.deserialize(blob)
        out = fn.call({k: jnp.asarray(v) for k, v in feeds.items()})
        return {k: np.asarray(v) for k, v in out.items()}

    def export_stablehlo_text(self, outputs: Sequence[str],
                              feed_specs: Dict[str, Tuple[Tuple[int, ...], str]]
                              ) -> Tuple[str, List[str]]:
        """Raw StableHLO MLIR of the compiled program + the positional arg
        order (sorted placeholder names). This is the form
        runtime/native.NativeRuntime.compile consumes directly — the
        north-star #4 seam: import → train → export → PJRT execute
        without jax in the serving process."""
        outputs = tuple(outputs)
        self._require_placeholders(feed_specs)
        ph_names = tuple(sorted(feed_specs))
        fn = self._build_fn(outputs, ph_names)
        variables, constants, _ = self._split_feeds({})

        def program(*placeholder_vals):
            feeds = dict(zip(ph_names, placeholder_vals))
            out = fn(variables, constants, feeds)
            return tuple(out[o] for o in outputs)

        specs = [jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
                 for _, (s, d) in sorted(feed_specs.items())]
        # keep_unused: the MLIR signature must carry EVERY declared
        # placeholder, or arg_order would misalign with main()'s params
        # when an output doesn't consume some feed.
        mlir = jax.jit(program, keep_unused=True).lower(*specs).compiler_ir(
            "stablehlo")
        return str(mlir), list(ph_names)


def _while_static_trip(sd: SameDiff, node: OpNode) -> Optional[int]:
    """Static trip count of a counter-bounded while, or None.

    Recognizes conds that are (conjunctions of) ``lt(counter, bound)``
    where each counter carry slot is updated by ``add(counter, step)``
    with a positive body-constant step, the bound is a cond-graph
    constant or a pass-through carry slot, and every needed init is a
    CONSTANT of the outer graph. This is exactly the loop shape TF/keras
    RNN imports produce (loop_counter < max_iterations AND time < T),
    and it lowers to ``lax.scan`` — reverse-differentiable (imported
    RNNs train) where lax.while_loop is not, and scan is the TPU-native
    loop form.
    """
    cond_sd = (node.subgraphs or {}).get("cond")
    body_sd = (node.subgraphs or {}).get("body")
    if cond_sd is None or body_sd is None:
        return None
    if cond_sd.branch_outputs is None or body_sd.branch_outputs is None:
        return None
    phc = [n for n, v in cond_sd._vars.items()
           if v.var_type == VariableType.PLACEHOLDER]
    phb = [n for n, v in body_sd._vars.items()
           if v.var_type == VariableType.PLACEHOLDER]
    if len(phc) != len(node.inputs) or len(phb) != len(node.inputs):
        return None
    slot_c = {n: i for i, n in enumerate(phc)}
    b_outs = body_sd.branch_outputs
    if len(b_outs) != len(node.inputs):
        return None

    def static_outer(j):
        name = node.inputs[j]
        v = sd._vars.get(name)
        if v is None or v.var_type != VariableType.CONSTANT:
            return None
        arr = np.asarray(sd._values[name])
        return arr.reshape(()).item() if arr.size == 1 else None

    def static_cond_const(name):
        v = cond_sd._vars.get(name)
        if v is None or v.var_type != VariableType.CONSTANT:
            return None
        arr = np.asarray(cond_sd._values[name])
        return arr.reshape(()).item() if arr.size == 1 else None

    def body_step(j):
        idx = body_sd._producer.get(b_outs[j])
        if idx is None:
            return None
        nd = body_sd._nodes[idx]
        if nd.op != "add" or len(nd.inputs) != 2:
            return None
        a, b = nd.inputs
        other = b if a == phb[j] else (a if b == phb[j] else None)
        if other is None:
            return None
        v = body_sd._vars.get(other)
        if v is None or v.var_type != VariableType.CONSTANT:
            return None
        arr = np.asarray(body_sd._values[other])
        step = arr.reshape(()).item() if arr.size == 1 else None
        return step if step is not None and step > 0 else None

    def analyze(name):
        idx = cond_sd._producer.get(name)
        if idx is None:
            return None
        nd = cond_sd._nodes[idx]
        if nd.op == "math.logical_and" and len(nd.inputs) == 2:
            left = analyze(nd.inputs[0])
            right = analyze(nd.inputs[1])
            return None if left is None or right is None else left + right
        if nd.op == "lt" and len(nd.inputs) == 2:
            j = slot_c.get(nd.inputs[0])
            if j is None:
                return None
            bound = static_cond_const(nd.inputs[1])
            if bound is None:
                m = slot_c.get(nd.inputs[1])
                if m is None or b_outs[m] != phb[m]:
                    return None  # bound must be invariant
                bound = static_outer(m)
            i0 = static_outer(j)
            step = body_step(j)
            if bound is None or i0 is None or step is None:
                return None
            # INTEGER counters only: a float counter's accumulated value
            # can disagree with ceil((bound-i0)/step) (0.1-steps hit
            # 10.000000000000002), and a silently-wrong trip count is
            # worse than staying on lax.while_loop
            if not (float(step).is_integer() and float(bound).is_integer()
                    and float(i0).is_integer()):
                return None
            return [max(0, -(-(int(bound) - int(i0)) // int(step)))]
        return None

    trips = analyze(cond_sd.branch_outputs[0])
    return None if trips is None else int(min(trips))


def _replay_call_node(sd: SameDiff, node: OpNode, fn, vals: List[Any]):
    if node.op == "__cond__":
        pred, *operands = vals
        tb = node.subgraphs["true"]._as_branch_fn()
        fb = node.subgraphs["false"]._as_branch_fn()
        return jax.lax.cond(pred, tb, fb, *operands)
    if node.op == "__while__":
        bg = node.subgraphs["body"]._as_branch_fn()
        trip = _while_static_trip(sd, node)
        if trip is not None:
            # counter-bounded loop -> lax.scan: differentiable, and the
            # TPU-native loop form (unrolled trip metadata for XLA)
            def step(carry, _):
                out = bg(*carry)
                return (out if isinstance(out, tuple) else (out,)), None

            final, _ = jax.lax.scan(step, tuple(vals), None, length=trip)
            return final
        cg = node.subgraphs["cond"]._as_branch_fn()
        carry = tuple(vals)

        def c(state):
            return cg(*state)

        def b(state):
            out = bg(*state)
            return out if isinstance(out, tuple) else (out,)

        return jax.lax.while_loop(c, b, carry)
    return _replay_call(fn, node, vals)


def _cond_impl(*a, **k):  # placeholder: handled in _replay_call_node
    raise RuntimeError("__cond__ replayed specially")


def _while_impl(*a, **k):
    raise RuntimeError("__while__ replayed specially")


# Registered at import time so graphs containing control flow execute after
# load() in a fresh process (not only in the process that recorded them).
register_op("__cond__", _cond_impl)
register_op("__while__", _while_impl)


class _UnknownStruct:
    """Shape-inference fallback: dtype may be known, shape is not."""

    def __init__(self, dtype=None):
        self.shape = None
        self.dtype = dtype


def _jsonable_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    def conv(v):
        if isinstance(v, (np.integer,)):
            return int(v)
        if isinstance(v, (np.floating,)):
            return float(v)
        if isinstance(v, (tuple, list)):
            return [conv(x) for x in v]
        if v is None or isinstance(v, (bool, int, float, str, dict)):
            return v
        raise TypeError(
            f"op attr {v!r} ({type(v).__name__}) is not serializable; "
            "pass arrays as SDVariables/constants")

    return {k: conv(v) for k, v in attrs.items()}


@dataclasses.dataclass
class TrainingConfig:
    """↔ org.nd4j.autodiff.samediff.TrainingConfig: updater, regularization,
    and the feature/label placeholder mapping."""

    loss_variable: str
    feature_placeholders: List[str] = dataclasses.field(default_factory=list)
    label_placeholders: List[str] = dataclasses.field(default_factory=list)
    updater: str = "adam"
    updater_args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    l1: float = 0.0
    l2: float = 0.0

    def placeholders(self, sd: SameDiff) -> List[str]:
        names = list(self.feature_placeholders) + list(self.label_placeholders)
        if not names:
            names = [n for n, v in sd._vars.items()
                     if v.var_type == VariableType.PLACEHOLDER]
        return names
