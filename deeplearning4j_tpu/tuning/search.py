"""Parameter spaces + candidate generators + the trial runner.

ref: arbiter ParameterSpace impls (ContinuousParameterSpace,
IntegerParameterSpace, DiscreteParameterSpace), CandidateGenerator
(RandomSearchGenerator, GridSearchCandidateGenerator), OptimizationRunner
+ ScoreFunction (SURVEY-era reference surface; arbiter lived in the
monorepo in the fork's era).
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np


# --- parameter spaces ------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Choice:
    """↔ DiscreteParameterSpace: one of a fixed set."""

    values: Sequence[Any]

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]

    def grid(self, points):
        return list(self.values)


@dataclasses.dataclass(frozen=True)
class Uniform:
    """↔ ContinuousParameterSpace (linear)."""

    low: float
    high: float

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))

    def grid(self, points):
        return [float(v) for v in np.linspace(self.low, self.high, points)]


@dataclasses.dataclass(frozen=True)
class LogUniform:
    """↔ ContinuousParameterSpace with exp-scale sampling (the learning-rate
    space shape)."""

    low: float
    high: float

    def sample(self, rng):
        return float(math.exp(rng.uniform(math.log(self.low),
                                          math.log(self.high))))

    def grid(self, points):
        return [float(v) for v in np.exp(
            np.linspace(math.log(self.low), math.log(self.high), points))]


@dataclasses.dataclass(frozen=True)
class IntRange:
    """↔ IntegerParameterSpace: integer in [low, high] inclusive."""

    low: int
    high: int

    def sample(self, rng):
        return int(rng.integers(self.low, self.high + 1))

    def grid(self, points):
        pts = np.unique(np.round(
            np.linspace(self.low, self.high, points)).astype(int))
        return [int(v) for v in pts]


_SPACE_TYPES = (Choice, Uniform, LogUniform, IntRange)


def sample_space(space: Dict[str, Any], rng) -> Dict[str, Any]:
    """Sample every parameter-space leaf; fixed values pass through."""
    out = {}
    for k, v in space.items():
        if isinstance(v, _SPACE_TYPES):
            out[k] = v.sample(rng)
        elif isinstance(v, dict):
            out[k] = sample_space(v, rng)
        else:
            out[k] = v
    return out


def grid_points(space: Dict[str, Any], points_per_axis: int = 3
                ) -> List[Dict[str, Any]]:
    """Cartesian product over every space leaf (↔ GridSearchCandidateGenerator).

    Nested dicts are handled structurally (key PATHS as tuples, so literal
    dots in parameter names survive).
    """
    flat: Dict[tuple, Any] = {}

    def _flatten(prefix: tuple, d):
        for k, v in d.items():
            if isinstance(v, dict):
                _flatten(prefix + (k,), v)
            else:
                flat[prefix + (k,)] = v

    _flatten((), space)
    axes = []
    for path, v in flat.items():
        vals = v.grid(points_per_axis) if isinstance(v, _SPACE_TYPES) else [v]
        axes.append([(path, val) for val in vals])
    out = []
    for combo in itertools.product(*axes):
        nested: Dict[str, Any] = {}
        for path, val in combo:
            cur = nested
            for p in path[:-1]:
                cur = cur.setdefault(p, {})
            cur[path[-1]] = val
        out.append(nested)
    return out


# --- candidate generators --------------------------------------------------


class RandomSearch:
    """↔ RandomSearchGenerator."""

    def __init__(self, space: Dict[str, Any], n_trials: int, seed: int = 0):
        self.space = space
        self.n_trials = n_trials
        self.seed = seed

    def candidates(self) -> Iterable[Dict[str, Any]]:
        rng = np.random.default_rng(self.seed)
        for _ in range(self.n_trials):
            yield sample_space(self.space, rng)


class GridSearch:
    """↔ GridSearchCandidateGenerator."""

    def __init__(self, space: Dict[str, Any], points_per_axis: int = 3):
        self.space = space
        self.points_per_axis = points_per_axis

    def candidates(self) -> Iterable[Dict[str, Any]]:
        return iter(grid_points(self.space, self.points_per_axis))


# --- runner ----------------------------------------------------------------


@dataclasses.dataclass
class TrialResult:
    params: Dict[str, Any]
    score: float
    seconds: float
    error: Optional[str] = None


class Tuner:
    """↔ OptimizationRunner: run candidates, score, keep the best.

    ``build_fn(params) -> (model, fit_kwargs)`` builds a fresh model per
    candidate; ``scorer(model, variables) -> float`` evaluates it
    (``mode``: 'max' e.g. accuracy, 'min' e.g. loss). A crashing candidate
    records its error and the search continues (arbiter behavior).
    """

    def __init__(self, build_fn: Callable, scorer: Callable,
                 *, mode: str = "max", max_seconds: Optional[float] = None):
        if mode not in ("max", "min"):
            raise ValueError(f"mode must be 'max'|'min', got {mode!r}")
        self.build_fn = build_fn
        self.scorer = scorer
        self.mode = mode
        self.max_seconds = max_seconds
        self.results: List[TrialResult] = []

    def fit(self, generator, train_iter, *, epochs: int = 1,
            listeners=None) -> TrialResult:
        from deeplearning4j_tpu.train.trainer import Trainer

        self.results = []  # per-search: a second fit() starts fresh
        deadline = (time.monotonic() + self.max_seconds
                    if self.max_seconds else None)
        for params in generator.candidates():
            if deadline and time.monotonic() > deadline:
                break
            t0 = time.monotonic()
            try:
                model, fit_kwargs = self.build_fn(params)
                trainer = Trainer(model, **(fit_kwargs or {}))
                ts = trainer.init_state()
                ts = trainer.fit(ts, train_iter, epochs=epochs,
                                 listeners=listeners)
                score = float(self.scorer(model, trainer.variables(ts)))
                self.results.append(TrialResult(
                    params, score, time.monotonic() - t0))
            except Exception as e:  # noqa: BLE001 - arbiter keeps searching
                self.results.append(TrialResult(
                    params, float("nan"), time.monotonic() - t0,
                    error=f"{type(e).__name__}: {e}"))
            if hasattr(train_iter, "reset"):
                train_iter.reset()
        ok = [r for r in self.results if r.error is None
              and not math.isnan(r.score)]
        if not ok:
            raise RuntimeError(
                "every candidate failed: "
                + "; ".join(r.error or "nan" for r in self.results[:3]))
        key = (max if self.mode == "max" else min)
        return key(ok, key=lambda r: r.score)

    def summary(self) -> str:
        lines = [f"{'score':>10}  {'secs':>6}  params"]
        order = sorted(
            [r for r in self.results if r.error is None],
            key=lambda r: r.score, reverse=self.mode == "max")
        for r in order:
            lines.append(f"{r.score:10.4f}  {r.seconds:6.1f}  {r.params}")
        for r in self.results:
            if r.error is not None:
                lines.append(f"{'FAILED':>10}  {r.seconds:6.1f}  "
                             f"{r.params} ({r.error})")
        return "\n".join(lines)
