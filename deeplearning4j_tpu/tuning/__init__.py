"""Hyperparameter search (↔ the reference-era Arbiter module:
MultiLayerSpace/ParameterSpace + RandomSearchGenerator/GridSearchCandidateGenerator
+ IOptimizationRunner with a ScoreFunction).

TPU-first simplification: a candidate is just a dict of sampled leaf
values; the user supplies ``build_fn(params) -> (model, trainer_kwargs)``
and the tuner drives ordinary Trainer fits — every trial is the same
compiled-step machinery as production training, no bespoke runner layer.
"""

from deeplearning4j_tpu.tuning.search import (
    Choice,
    GridSearch,
    IntRange,
    LogUniform,
    RandomSearch,
    TrialResult,
    Tuner,
    Uniform,
    grid_points,
    sample_space,
)

__all__ = [
    "Choice", "Uniform", "LogUniform", "IntRange",
    "sample_space", "grid_points",
    "RandomSearch", "GridSearch", "Tuner", "TrialResult",
]
