"""Decoder-only causal language model (GPT family).

The reference's only text-generation model is TextGenerationLSTM
(rnnTimeStep char-RNN, SURVEY §2.7 zoo row); this is its transformer-era
counterpart, required by the build's first-class long-context story
(task §5 / SURVEY §5.7): causal flash attention (Pallas under the
auto-dispatch policy at long T), optional ring/Ulysses sequence
parallelism on a `seq` mesh axis, remat for deep stacks, and a KV-cache
autoregressive decoder that compiles the WHOLE generation loop into one
`lax.scan` program — the transformer analogue of the compiled char-RNN
generation in nn/generation.py (one dispatch per sequence, not per
token; through a ~69 ms-round-trip interconnect that is the difference
between usable and unusable sampling).

Training reuses TransformerEncoderBlock (pre-LN, causal=True) so every
Trainer feature (donation, bf16 policy, NaN guard, chained bench
windows) applies unchanged; the cached decode step re-implements the
block's forward over the same param tree, and a parity test pins its
logits to the full forward's at every position
(tests/test_gpt.py::test_cached_decode_matches_full_forward).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration, register_config
from deeplearning4j_tpu.nn.layers.attention import TransformerEncoderBlock
from deeplearning4j_tpu.ops import loss as losses
from deeplearning4j_tpu.ops import nn as opsnn
from deeplearning4j_tpu.train.updaters import Adam


@register_config
@dataclass
class GptConfig:
    """Architecture config (JSON round-trip via the config registry)."""

    vocab_size: int = 50257
    hidden: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate: int = 3072
    max_position: int = 1024
    dropout: float = 0.1
    attention_dropout: float = 0.1
    activation: str = "gelu"
    eps: float = 1e-5
    initializer_range: float = 0.02
    remat: bool = False
    # "ring" | "ulysses" | None — P9 sequence parallelism for long-context
    # training (takes effect inside a parallel.sequence.sequence_mesh).
    sequence_parallel: Optional[str] = None
    net: NeuralNetConfiguration = field(
        default_factory=lambda: NeuralNetConfiguration(updater=Adam(3e-4))
    )


class Gpt:
    """Causal transformer LM: Trainer-compatible (init/apply/loss_fn) plus
    a compiled KV-cache generator."""

    def __init__(self, config: GptConfig):
        self.config = config
        self.net = config.net
        self._block = TransformerEncoderBlock(
            num_heads=config.num_heads,
            intermediate=config.intermediate,
            activation=config.activation,
            dropout=config.dropout,
            attention_dropout=config.attention_dropout,
            causal=True,
            post_ln=False,  # pre-LN: stable for deep decoder stacks
            eps=config.eps,
            remat=config.remat,
            sequence_parallel=config.sequence_parallel,
        )

    # -- construction ------------------------------------------------------

    def init(self, seed: Optional[int] = None) -> Dict[str, Any]:
        c = self.config
        seed = self.net.seed if seed is None else seed
        rng = jax.random.key(seed)
        dtype = jnp.dtype(self.net.dtype)
        std = c.initializer_range

        def trunc(key, shape):
            return std * jax.random.truncated_normal(key, -2.0, 2.0, shape,
                                                     dtype)

        ks = jax.random.split(rng, 4 + c.num_layers)
        params: Dict[str, Any] = {
            "embeddings": {
                "word": trunc(ks[0], (c.vocab_size, c.hidden)),
                "position": trunc(ks[1], (c.max_position, c.hidden)),
            },
            # final pre-head LayerNorm (GPT-2 style); decoder weight is
            # tied to the word embedding, only a bias is learned
            "final": {
                "ln_gamma": jnp.ones((c.hidden,), dtype),
                "ln_beta": jnp.zeros((c.hidden,), dtype),
                "out_b": jnp.zeros((c.vocab_size,), dtype),
            },
        }
        for i in range(c.num_layers):
            p, _ = self._block.init(ks[4 + i], (c.max_position, c.hidden),
                                    dtype)
            params[f"layer_{i}"] = p
        return {"params": params, "state": {}}

    # -- pure functions ----------------------------------------------------

    def encode(self, params, ids, *, train=False, rng=None, mask=None):
        """[N,T] int32 → hidden [N,T,H] (pre-head LN applied)."""
        c = self.config
        t = ids.shape[1]
        emb = params["embeddings"]
        x = opsnn.embedding_lookup(emb["word"], ids)
        x = x + emb["position"][:t][None, :, :]
        if train and c.dropout > 0.0 and rng is not None:
            x = opsnn.dropout(x, c.dropout, jax.random.fold_in(rng, 999))
        for i in range(c.num_layers):
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            x, _ = self._block.apply(params[f"layer_{i}"], {}, x,
                                     train=train, rng=lrng, mask=mask)
        f = params["final"]
        return opsnn.layer_norm(x, f["ln_gamma"], f["ln_beta"], eps=c.eps)

    def logits(self, params, hidden):
        return (jnp.einsum("nth,vh->ntv", hidden,
                           params["embeddings"]["word"])
                + params["final"]["out_b"])

    def apply(self, variables, features, *, train=False, rng=None):
        """Returns (logits [N,T,V], state)."""
        if isinstance(features, dict):
            ids = features["token_ids"]
            mask = features.get("mask")
        else:
            ids, mask = features, None
        h = self.encode(variables["params"], ids, train=train, rng=rng,
                        mask=mask)
        return self.logits(variables["params"], h), variables.get("state", {})

    def loss_fn(self, params, state, batch, rng=None):
        """Next-token cross entropy. batch["features"]["token_ids"] [N,T];
        optional features["mask"] [N,T] excludes padding from loss and
        attention; optional batch["labels"] overrides the shifted ids."""
        features = batch["features"]
        if not isinstance(features, dict):
            features = {"token_ids": features}
        ids = features["token_ids"]
        mask = features.get("mask")
        h = self.encode(params, ids, train=True, rng=rng, mask=mask)
        lg = self.logits(params, h)[:, :-1]
        labels = batch.get("labels")
        if labels is None:
            labels = ids[:, 1:]
        w = (jnp.ones(labels.shape, jnp.float32) if mask is None
             else mask[:, 1:].astype(jnp.float32))
        per_tok = losses.sparse_softmax_cross_entropy(lg, labels,
                                                      reduction="none")
        loss = jnp.sum(per_tok * w) / jnp.maximum(jnp.sum(w), 1.0)
        return loss, (state, {"loss": loss})

    def loss_weight(self, batch):
        """Total loss-weight of ``batch`` — non-padding next-token
        positions. The trainer's grad-accumulation scan uses this to
        combine microbatches exactly as the full-batch weighted mean
        would, even when mask density varies across microbatches.

        Deliberately UNclamped (unlike loss_fn's max(Σw,1) divide-guard):
        a fully-padded microbatch has loss 0 and must contribute weight 0
        to the combination, not a phantom 1 — w·loss = Σ per-token loss
        holds exactly either way."""
        features = batch["features"]
        if not isinstance(features, dict):
            features = {"token_ids": features}
        ids = features["token_ids"]
        mask = features.get("mask")
        if mask is None:
            n, t = ids.shape
            return jnp.float32(n * (t - 1))
        return jnp.sum(mask[:, 1:].astype(jnp.float32))

    def num_params(self, variables) -> int:
        return sum(p.size for p in
                   jax.tree_util.tree_leaves(variables["params"]))

    # -- KV-cache decoding -------------------------------------------------

    def init_cache(self, batch_size: int, max_len: int, dtype=jnp.float32):
        """Per-layer K/V ring buffers [N, heads, max_len, head_dim]."""
        c = self.config
        hd = c.hidden // c.num_heads
        shape = (batch_size, c.num_heads, max_len, hd)
        return [{"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
                for _ in range(c.num_layers)]

    def _block_step(self, p, cache, x_t, pos):
        """One token through one block with cached K/V.

        x_t: [N,H]; pos: scalar int32 (0-based position of this token).
        Re-implements TransformerEncoderBlock._forward (pre-LN branch) —
        parity pinned by test_cached_decode_matches_full_forward.
        """
        c = self.config
        h = c.num_heads
        eps = c.eps

        def ln(v, which):
            return opsnn.layer_norm(v, p[f"{which}_gamma"],
                                    p[f"{which}_beta"], eps=eps)

        ap = p["attention"]
        a_in = ln(x_t, "ln1")  # [N,H]
        n, e = a_in.shape
        hd = e // h

        def heads(z):
            return z.reshape(n, h, 1, hd)  # [N,h,1,hd] from [N, h*hd]

        q = heads(opsnn.linear(a_in, ap["Wq"], ap.get("bq")))
        k = heads(opsnn.linear(a_in, ap["Wk"], ap.get("bk")))
        v = heads(opsnn.linear(a_in, ap["Wv"], ap.get("bv")))
        kc = jax.lax.dynamic_update_slice(cache["k"], k, (0, 0, pos, 0))
        vc = jax.lax.dynamic_update_slice(cache["v"], v, (0, 0, pos, 0))
        scores = jnp.einsum("nhqd,nhld->nhql", q, kc) / jnp.sqrt(
            jnp.asarray(hd, q.dtype))
        # causal-by-construction: only slots <= pos are live
        live = (jnp.arange(kc.shape[2]) <= pos)[None, None, None, :]
        scores = jnp.where(live, scores, jnp.finfo(scores.dtype).min)
        att = jax.nn.softmax(scores, axis=-1)
        y = jnp.einsum("nhql,nhld->nhqd", att, vc).reshape(n, e)
        a = opsnn.linear(y, ap["Wo"], ap.get("bo"))
        x = x_t + a
        f_in = ln(x, "ln2")
        f = opsnn.linear(f_in, p["W1"], p["b1"])
        f = get_activation(c.activation)(f)
        f = opsnn.linear(f, p["W2"], p["b2"])
        return x + f, {"k": kc, "v": vc}

    def decode_step(self, params, caches, ids_t, pos):
        """One decode step: ids_t [N] int32 at position pos → (logits [N,V],
        updated caches)."""
        c = self.config
        emb = params["embeddings"]
        x = opsnn.embedding_lookup(emb["word"], ids_t)  # [N,H]
        x = x + jax.lax.dynamic_slice_in_dim(emb["position"], pos, 1, 0)[0]
        new_caches = []
        for i in range(c.num_layers):
            x, cc = self._block_step(params[f"layer_{i}"], caches[i], x, pos)
            new_caches.append(cc)
        f = params["final"]
        hfin = opsnn.layer_norm(x, f["ln_gamma"], f["ln_beta"], eps=c.eps)
        lg = hfin @ params["embeddings"]["word"].T + f["out_b"]
        return lg, new_caches

    # -- continuous-batching decode (serving/generation.py) ----------------

    def _block_step_slots(self, p, cache, x_t, pos):
        """One token through one block with cached K/V and PER-ROW
        positions — the continuous-batching twin of :meth:`_block_step`,
        where every row of the batch is an independent sequence at its
        own depth (``pos`` is [N] int32, not a scalar). Parity with the
        scalar path is pinned by
        tests/test_generation_serving.py::test_slot_decode_matches_scalar.
        """
        c = self.config
        h = c.num_heads
        eps = c.eps

        def ln(v, which):
            return opsnn.layer_norm(v, p[f"{which}_gamma"],
                                    p[f"{which}_beta"], eps=eps)

        ap = p["attention"]
        a_in = ln(x_t, "ln1")  # [N,H]
        n, e = a_in.shape
        hd = e // h

        def heads(z):
            return z.reshape(n, h, hd)  # [N,h,hd] from [N, h*hd]

        q = heads(opsnn.linear(a_in, ap["Wq"], ap.get("bq")))
        k = heads(opsnn.linear(a_in, ap["Wk"], ap.get("bk")))
        v = heads(opsnn.linear(a_in, ap["Wv"], ap.get("bv")))
        rows = jnp.arange(n)
        # per-row scatter: row i's new K/V lands at its own pos[i]
        kc = cache["k"].at[rows, :, pos, :].set(k)
        vc = cache["v"].at[rows, :, pos, :].set(v)
        scores = jnp.einsum("nhd,nhld->nhl", q, kc) / jnp.sqrt(
            jnp.asarray(hd, q.dtype))
        # causal-by-construction, per row: only slots <= pos[i] are live
        live = jnp.arange(kc.shape[2])[None, None, :] <= pos[:, None, None]
        scores = jnp.where(live, scores, jnp.finfo(scores.dtype).min)
        att = jax.nn.softmax(scores, axis=-1)
        y = jnp.einsum("nhl,nhld->nhd", att, vc).reshape(n, e)
        a = opsnn.linear(y, ap["Wo"], ap.get("bo"))
        x = x_t + a
        f_in = ln(x, "ln2")
        f = opsnn.linear(f_in, p["W1"], p["b1"])
        f = get_activation(c.activation)(f)
        f = opsnn.linear(f, p["W2"], p["b2"])
        return x + f, {"k": kc, "v": vc}

    def decode_step_slots(self, params, caches, ids_t, pos):
        """One iteration-level decode step over independent sequences:
        ids_t [N] int32, pos [N] int32 (each row's own 0-based position)
        → (logits [N,V], updated caches). Rows are decode *slots* —
        sequences at different depths batched into one device step, the
        core primitive of the continuous-batching serving engine."""
        c = self.config
        emb = params["embeddings"]
        x = opsnn.embedding_lookup(emb["word"], ids_t)  # [N,H]
        x = x + emb["position"][pos]                    # per-row gather
        new_caches = []
        for i in range(c.num_layers):
            x, cc = self._block_step_slots(params[f"layer_{i}"], caches[i],
                                           x, pos)
            new_caches.append(cc)
        f = params["final"]
        hfin = opsnn.layer_norm(x, f["ln_gamma"], f["ln_beta"], eps=c.eps)
        lg = hfin @ params["embeddings"]["word"].T + f["out_b"]
        return lg, new_caches

    def prefill_chunk(self, params, ids):
        """Whole-prompt prefill with full causal self-attention:
        ids [N,P] int32 → (logits [N,P,V], per-layer K/V
        ``[{"k": [N,h,P,hd], "v": ...}]``). One matmul-bound program
        instead of a P-step decode scan — the compute-shaped half of the
        prefill/decode split (decode is memory-bound; cuDNN-paper
        batched-primitive framing). Re-implements the pre-LN block over
        the same param tree; logits parity with the cached decode scan
        is pinned by tests/test_generation_serving.py."""
        c = self.config
        h = c.num_heads
        emb = params["embeddings"]
        n, pl = ids.shape
        x = opsnn.embedding_lookup(emb["word"], ids)
        x = x + emb["position"][:pl][None, :, :]
        causal = jnp.tril(jnp.ones((pl, pl), bool))[None, None]
        kvs = []
        for i in range(c.num_layers):
            p = params[f"layer_{i}"]

            def ln(v, which, p=p):
                return opsnn.layer_norm(v, p[f"{which}_gamma"],
                                        p[f"{which}_beta"], eps=c.eps)

            ap = p["attention"]
            a_in = ln(x, "ln1")                      # [N,P,E]
            e = a_in.shape[-1]
            hd = e // h

            def heads(z):
                # [N,P,h*hd] -> [N,h,P,hd]; feature layout head-major,
                # matching _block_step's reshape(n, h, 1, hd)
                return z.reshape(n, pl, h, hd).transpose(0, 2, 1, 3)

            q = heads(opsnn.linear(a_in, ap["Wq"], ap.get("bq")))
            k = heads(opsnn.linear(a_in, ap["Wk"], ap.get("bk")))
            v = heads(opsnn.linear(a_in, ap["Wv"], ap.get("bv")))
            scores = jnp.einsum("nhqd,nhkd->nhqk", q, k) / jnp.sqrt(
                jnp.asarray(hd, q.dtype))
            scores = jnp.where(causal, scores,
                               jnp.finfo(scores.dtype).min)
            att = jax.nn.softmax(scores, axis=-1)
            y = jnp.einsum("nhqk,nhkd->nhqd", att, v)
            y = y.transpose(0, 2, 1, 3).reshape(n, pl, e)
            x = x + opsnn.linear(y, ap["Wo"], ap.get("bo"))
            f_in = ln(x, "ln2")
            f = opsnn.linear(f_in, p["W1"], p["b1"])
            f = get_activation(c.activation)(f)
            x = x + opsnn.linear(f, p["W2"], p["b2"])
            kvs.append({"k": k, "v": v})
        fin = params["final"]
        hfin = opsnn.layer_norm(x, fin["ln_gamma"], fin["ln_beta"],
                                eps=c.eps)
        lg = (jnp.einsum("nth,vh->ntv", hfin, emb["word"])
              + fin["out_b"])
        return lg, kvs

    def generate(self, variables, prime_ids, *, n_steps: int, rng,
                 temperature: float = 1.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 max_len: Optional[int] = None):
        """Sample n_steps continuation tokens after prime_ids [N,T0].

        Prefill runs the cached decoder over the prime with a lax.scan
        (teacher forcing), then a second scan samples; BOTH loops live in
        one jitted program per (shape, n_steps) — no per-token dispatch.
        temperature=0 is greedy argmax; ``top_k`` keeps the k most likely
        tokens, ``top_p`` nucleus-truncates to the smallest set with
        cumulative probability ≥ p (both before the categorical draw;
        combinable — top_k filters first). Returns [N, n_steps] int32.
        """
        params = variables["params"]
        n, t0 = prime_ids.shape
        total = max_len or (t0 + n_steps)
        if total < t0 + n_steps:
            raise ValueError(
                f"max_len {total} < prime {t0} + n_steps {n_steps}: the KV "
                "cache would clamp out-of-range writes to its last slot and "
                "sample from stale keys")
        if total > self.config.max_position:
            raise ValueError(
                f"generation length {total} exceeds max_position "
                f"{self.config.max_position}")
        if top_k is not None and top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        # normalize no-op filters so they share the plain program's jit
        # cache entry instead of recompiling identical behavior
        if top_k is not None and top_k >= self.config.vocab_size:
            top_k = None
        if top_p is not None and top_p >= 1.0:
            top_p = None
        fn = _generate_fn_cache(
            self, t0, n_steps, total, float(temperature),
            None if top_k is None else int(top_k),
            None if top_p is None else float(top_p))
        return fn(params, jnp.asarray(prime_ids, jnp.int32), rng)

    def beam_search(self, variables, prime_ids, *, n_steps: int,
                    beam_size: int = 4, length_penalty: float = 0.0,
                    eos_id: Optional[int] = None,
                    max_len: Optional[int] = None):
        """Beam-search n_steps continuation tokens after prime_ids [N,T0].

        Returns (sequences [N, beam_size, n_steps] int32, scores
        [N, beam_size] float32), best beam first. Scores are summed
        next-token log-probabilities; with ``length_penalty`` α > 0 they
        are GNMT-normalized by ((5+len)/6)^α. ``eos_id`` freezes a beam
        once it emits eos (it then continues on eos at logprob 0). The
        whole search — prefill, expansion, cache reordering, backtrace —
        compiles as one XLA program per shape (no per-token dispatch).
        beam_size=1 degenerates to greedy decoding."""
        params = variables["params"]
        n, t0 = prime_ids.shape
        total = max_len or (t0 + n_steps)
        if total < t0 + n_steps:
            raise ValueError(
                f"max_len {total} < prime {t0} + n_steps {n_steps}")
        if total > self.config.max_position:
            raise ValueError(
                f"generation length {total} exceeds max_position "
                f"{self.config.max_position}")
        if beam_size < 1:
            raise ValueError(f"beam_size must be >= 1, got {beam_size}")
        if beam_size > self.config.vocab_size:
            raise ValueError(
                f"beam_size {beam_size} > vocab {self.config.vocab_size}")
        if n_steps < 1:
            raise ValueError(f"n_steps must be >= 1, got {n_steps}")
        if length_penalty < 0:
            raise ValueError(
                f"length_penalty must be >= 0, got {length_penalty}")
        key = (t0, n_steps, total, int(beam_size), float(length_penalty),
               None if eos_id is None else int(eos_id))
        fn = _jit_cache(self, "_beam_cache", key, lambda: _build_beam_search_fn(
            self, t0, n_steps, total, int(beam_size),
            float(length_penalty), eos_id))
        return fn(params, jnp.asarray(prime_ids, jnp.int32))


def _jit_cache(model, attr: str, key, build):
    """Per-model jit-program cache (generate/beam_search): repeated calls
    with the same static config never retrace."""
    cache = getattr(model, attr, None)
    if cache is None:
        cache = {}
        setattr(model, attr, cache)
    if key not in cache:
        cache[key] = build()
    return cache[key]


def _prefill(model: "Gpt", params, prime, t0: int, total: int):
    """Cached-decoder prefill over the prime (teacher forcing, one scan).
    Returns (caches, last-position logits). Shared by generate and
    beam_search so KV-parity is pinned once."""
    caches = model.init_cache(
        prime.shape[0], total, dtype=params["embeddings"]["word"].dtype)

    def step(carry, t):
        caches = carry
        lg, caches = model.decode_step(params, caches, prime[:, t], t)
        return caches, lg

    caches, lgs = jax.lax.scan(step, caches, jnp.arange(t0))
    return caches, lgs[-1]


def _truncate_logits(lg, top_k: Optional[int], top_p: Optional[float]):
    """Mask logits outside the top-k set and/or the nucleus (top-p) set to
    -inf. Pure function of static (k, p); vocab axis last."""
    neg = jnp.finfo(lg.dtype).min
    if top_k is not None and top_k < lg.shape[-1]:
        kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
        lg = jnp.where(lg < kth, neg, lg)
    if top_p is not None and top_p < 1.0:
        sorted_lg = jnp.sort(lg, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_lg, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens while the cumulative mass BEFORE them is < p (the
        # first token is always kept)
        keep = jnp.concatenate(
            [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], axis=-1) < top_p
        # threshold = smallest kept sorted logit
        thresh = jnp.min(jnp.where(keep, sorted_lg, jnp.inf), axis=-1,
                         keepdims=True)
        lg = jnp.where(lg < thresh, neg, lg)
    return lg


def _build_generate_fn(model: Gpt, t0: int, n_steps: int, total: int,
                       temperature: float, top_k: Optional[int] = None,
                       top_p: Optional[float] = None):
    def run(params, prime, rng):
        # cache dtype follows the params (bf16 nets project bf16 K/V)
        caches, last_logits = _prefill(model, params, prime, t0, total)

        def sample(lg, key):
            if temperature == 0.0:
                return jnp.argmax(lg, axis=-1).astype(jnp.int32)
            # temperature FIRST, then nucleus/top-k on the tempered
            # distribution (standard semantics: the kept set holds mass p
            # of the distribution actually sampled)
            lg = lg / jnp.asarray(temperature, lg.dtype)
            lg = _truncate_logits(lg, top_k, top_p)
            return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)

        def step(carry, i):
            caches, lg, key = carry
            key, sub = jax.random.split(key)
            tok = sample(lg, sub)
            lg2, caches = model.decode_step(params, caches, tok, t0 + i)
            return (caches, lg2, key), tok

        (_, _, _), toks = jax.lax.scan(
            step, (caches, last_logits, rng), jnp.arange(n_steps))
        return toks.T  # [N, n_steps]

    return jax.jit(run)


def _generate_fn_cache(model: Gpt, t0: int, n_steps: int, total: int,
                       temperature: float, top_k: Optional[int] = None,
                       top_p: Optional[float] = None):
    """Per-model jit cache so repeated sampling never retraces."""
    return _jit_cache(
        model, "_gen_cache", (t0, n_steps, total, temperature, top_k, top_p),
        lambda: _build_generate_fn(model, t0, n_steps, total, temperature,
                                   top_k, top_p))


def _build_beam_search_fn(model: Gpt, t0: int, n_steps: int, total: int,
                          beam_size: int, length_penalty: float,
                          eos_id: Optional[int]):
    """Compiled beam search: prefill scan at beam 1, tile the KV caches to
    ``beam_size`` rows, then ONE lax.scan of expand→top-k(B·V)→reorder
    steps with parent backtrace — the whole search is a single XLA
    program (↔ the reference SameDiff's beam decoding, without per-step
    host dispatch). Finished beams (eos) continue on eos with logprob 0,
    the standard freeze."""
    B = beam_size
    neg = -1e30

    def run(params, prime):
        n = prime.shape[0]
        caches, last_logits = _prefill(model, params, prime, t0, total)
        v = last_logits.shape[-1]
        logp0 = jax.nn.log_softmax(last_logits.astype(jnp.float32), axis=-1)

        caches = jax.tree_util.tree_map(
            lambda x: jnp.repeat(x, B, axis=0), caches)
        # first expansion from the (identical) prefix only once — top-B
        # tokens of the prime's next-token distribution seed the beams
        scores, tok0 = jax.lax.top_k(logp0, B)          # [N,B]
        tok0 = tok0.astype(jnp.int32)
        finished = (tok0 == eos_id) if eos_id is not None \
            else jnp.zeros((n, B), bool)
        lengths = jnp.ones((n, B), jnp.int32)

        def step(carry, i):
            caches, scores, finished, lengths, tok = carry
            lg, caches = model.decode_step(
                params, caches, tok.reshape(n * B), t0 + i)
            lp = jax.nn.log_softmax(
                lg.reshape(n, B, v).astype(jnp.float32), axis=-1)
            if eos_id is not None:
                eos_only = jnp.where(
                    jnp.arange(v)[None, None, :] == eos_id, 0.0, neg)
                lp = jnp.where(finished[..., None], eos_only, lp)
            flat = (scores[..., None] + lp).reshape(n, B * v)
            new_scores, idx = jax.lax.top_k(flat, B)    # [N,B]
            parent = idx // v
            new_tok = (idx % v).astype(jnp.int32)
            rows = (jnp.arange(n)[:, None] * B + parent).reshape(-1)
            caches = jax.tree_util.tree_map(lambda x: x[rows], caches)
            new_fin = jnp.take_along_axis(finished, parent, axis=1)
            new_len = jnp.take_along_axis(lengths, parent, axis=1) \
                + jnp.where(new_fin, 0, 1)
            if eos_id is not None:
                new_fin = new_fin | (new_tok == eos_id)
            return ((caches, new_scores, new_fin, new_len, new_tok),
                    (new_tok, parent))

        # iteration i decodes the PREVIOUS token (first: tok0 at slot t0)
        # and expands to the next one — n_steps-1 expansions after tok0
        (caches, scores, finished, lengths, _), (toks, parents) = \
            jax.lax.scan(step, (caches, scores, finished, lengths, tok0),
                         jnp.arange(n_steps - 1))

        # backtrace the parent chain (newest step first)
        def back(beam_idx, x):
            tok_t, parent_t = x
            sel = jnp.take_along_axis(tok_t, beam_idx, axis=1)
            return jnp.take_along_axis(parent_t, beam_idx, axis=1), sel

        init_idx = jnp.tile(jnp.arange(B)[None, :], (n, 1))
        beam_idx, rev = jax.lax.scan(back, init_idx,
                                     (toks[::-1], parents[::-1]))
        first = jnp.take_along_axis(tok0, beam_idx, axis=1)
        seqs = jnp.concatenate([first[None], rev[::-1]], axis=0)
        seqs = jnp.moveaxis(seqs, 0, 2)                 # [N,B,n_steps]
        final = scores
        if length_penalty:
            final = final / (((5.0 + lengths.astype(jnp.float32)) / 6.0)
                             ** length_penalty)
        order = jnp.argsort(-final, axis=1)
        seqs = jnp.take_along_axis(seqs, order[..., None], axis=1)
        final = jnp.take_along_axis(final, order, axis=1)
        return seqs, final

    return jax.jit(run)


def gpt2_small(**kw) -> Gpt:
    """GPT-2 small dims (12L/768H/12A, 1024 ctx)."""
    return Gpt(GptConfig(**kw))


def gpt_tiny(**kw) -> Gpt:
    """2L/64H/2A toy config for tests and CPU runs."""
    kw.setdefault("hidden", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("intermediate", 128)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position", 64)
    kw.setdefault("dropout", 0.0)
    kw.setdefault("attention_dropout", 0.0)
    return Gpt(GptConfig(**kw))


def gpt_long(**kw) -> Gpt:
    """Long-context config: ring-attention sequence parallelism + remat
    (train at T ≫ single-chip HBM limits on a `seq` mesh axis).

    Positions are learned absolute embeddings (GPT-2 convention — the
    [max_position, H] table is ~25M params at default dims); a rotary
    variant would shrink that and extrapolate, at the cost of diverging
    from the block layout every importer/test pins — future work, noted
    honestly rather than half-built."""
    kw.setdefault("sequence_parallel", "ring")
    kw.setdefault("remat", True)
    kw.setdefault("max_position", 32768)
    return Gpt(GptConfig(**kw))
