"""Classic sequential CNN zoo entries.

ref: org.deeplearning4j.zoo.model.{AlexNet, VGG16, VGG19, SimpleCNN,
Darknet19, TextGenerationLSTM} — each a MultiLayerNetwork/ComputationGraph
builder in the reference zoo; here each is a SequentialConfig factory whose
training step compiles to one XLA program (NHWC layout for the MXU).
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.config import NeuralNetConfiguration, SequentialConfig
from deeplearning4j_tpu.nn.layers import (
    LSTM,
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    Flatten,
    GlobalPooling,
    LocalResponseNormalization,
    LossLayer,
    OutputLayer,
    Pooling2D,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.model import SequentialModel


def alexnet_config(*, num_classes: int = 1000, input_shape=(224, 224, 3),
                   updater=None, seed: int = 12345) -> SequentialConfig:
    """↔ zoo AlexNet (one-tower variant with LRN, as in the reference zoo)."""
    net = NeuralNetConfiguration(seed=seed, updater=updater, weight_init="relu")
    layers = [
        Conv2D(filters=96, kernel=11, stride=4, padding="SAME", activation="relu"),
        LocalResponseNormalization(),
        Pooling2D(pool_type="max", window=3, stride=2),
        Conv2D(filters=256, kernel=5, stride=1, padding="SAME", activation="relu"),
        LocalResponseNormalization(),
        Pooling2D(pool_type="max", window=3, stride=2),
        Conv2D(filters=384, kernel=3, activation="relu"),
        Conv2D(filters=384, kernel=3, activation="relu"),
        Conv2D(filters=256, kernel=3, activation="relu"),
        Pooling2D(pool_type="max", window=3, stride=2),
        Flatten(),
        Dense(units=4096, activation="relu"),
        Dropout(rate=0.5),
        Dense(units=4096, activation="relu"),
        Dropout(rate=0.5),
        OutputLayer(units=num_classes, activation="softmax", loss="mcxent"),
    ]
    return SequentialConfig(net=net, layers=layers, input_shape=input_shape)


def _vgg_blocks(spec):
    layers = []
    for n_convs, filters in spec:
        for _ in range(n_convs):
            layers.append(Conv2D(filters=filters, kernel=3, padding="SAME",
                                 activation="relu"))
        layers.append(Pooling2D(pool_type="max", window=2, stride=2))
    return layers


def vgg16_config(*, num_classes: int = 1000, input_shape=(224, 224, 3),
                 updater=None, seed: int = 12345) -> SequentialConfig:
    """↔ zoo VGG16."""
    net = NeuralNetConfiguration(seed=seed, updater=updater, weight_init="relu")
    layers = _vgg_blocks([(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)])
    layers += [
        Flatten(),
        Dense(units=4096, activation="relu"),
        Dropout(rate=0.5),
        Dense(units=4096, activation="relu"),
        Dropout(rate=0.5),
        OutputLayer(units=num_classes, activation="softmax", loss="mcxent"),
    ]
    return SequentialConfig(net=net, layers=layers, input_shape=input_shape)


def vgg19_config(*, num_classes: int = 1000, input_shape=(224, 224, 3),
                 updater=None, seed: int = 12345) -> SequentialConfig:
    """↔ zoo VGG19."""
    net = NeuralNetConfiguration(seed=seed, updater=updater, weight_init="relu")
    layers = _vgg_blocks([(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)])
    layers += [
        Flatten(),
        Dense(units=4096, activation="relu"),
        Dropout(rate=0.5),
        Dense(units=4096, activation="relu"),
        Dropout(rate=0.5),
        OutputLayer(units=num_classes, activation="softmax", loss="mcxent"),
    ]
    return SequentialConfig(net=net, layers=layers, input_shape=input_shape)


def simplecnn_config(*, num_classes: int = 10, input_shape=(48, 48, 3),
                     updater=None, seed: int = 12345) -> SequentialConfig:
    """↔ zoo SimpleCNN (small conv stack used for sanity workloads)."""
    net = NeuralNetConfiguration(seed=seed, updater=updater, weight_init="relu")
    layers = [
        Conv2D(filters=16, kernel=3, activation="relu"),
        BatchNorm(),
        Conv2D(filters=16, kernel=3, activation="relu"),
        BatchNorm(),
        Pooling2D(pool_type="max", window=2),
        Conv2D(filters=32, kernel=3, activation="relu"),
        BatchNorm(),
        Conv2D(filters=32, kernel=3, activation="relu"),
        BatchNorm(),
        Pooling2D(pool_type="max", window=2),
        Flatten(),
        Dense(units=128, activation="relu"),
        Dropout(rate=0.5),
        OutputLayer(units=num_classes, activation="softmax", loss="mcxent"),
    ]
    return SequentialConfig(net=net, layers=layers, input_shape=input_shape)


def darknet19_config(*, num_classes: int = 1000, input_shape=(224, 224, 3),
                     updater=None, seed: int = 12345) -> SequentialConfig:
    """↔ zoo Darknet19 (conv-bn-leakyrelu stacks, global avg pool head)."""
    net = NeuralNetConfiguration(seed=seed, updater=updater, weight_init="relu")

    def cb(filters, kernel):
        return [
            Conv2D(filters=filters, kernel=kernel, use_bias=False),
            BatchNorm(activation="leakyrelu"),
        ]

    layers = []
    layers += cb(32, 3) + [Pooling2D(pool_type="max", window=2)]
    layers += cb(64, 3) + [Pooling2D(pool_type="max", window=2)]
    layers += cb(128, 3) + cb(64, 1) + cb(128, 3)
    layers += [Pooling2D(pool_type="max", window=2)]
    layers += cb(256, 3) + cb(128, 1) + cb(256, 3)
    layers += [Pooling2D(pool_type="max", window=2)]
    layers += cb(512, 3) + cb(256, 1) + cb(512, 3) + cb(256, 1) + cb(512, 3)
    layers += [Pooling2D(pool_type="max", window=2)]
    layers += cb(1024, 3) + cb(512, 1) + cb(1024, 3) + cb(512, 1) + cb(1024, 3)
    layers += [
        Conv2D(filters=num_classes, kernel=1),
        GlobalPooling(pool_type="avg"),
        # conv10 already maps to num_classes — parameter-free softmax head
        LossLayer(activation="softmax", loss="mcxent"),
    ]
    return SequentialConfig(net=net, layers=layers, input_shape=input_shape)


def text_generation_lstm_config(*, vocab_size: int = 77, hidden: int = 256,
                                seq_len: int = 64, updater=None,
                                seed: int = 12345, graves: bool = True,
                                backend: str = "xla") -> SequentialConfig:
    """↔ zoo TextGenerationLSTM (char-RNN; benchmark config #3 uses the
    GravesLSTM/peephole variant on the Pallas scan path).

    Input: one-hot chars [N, T, vocab]; output: next-char softmax per step.
    """
    from deeplearning4j_tpu.nn.layers import GravesLSTM as GravesLSTMLayer

    net = NeuralNetConfiguration(seed=seed, updater=updater, weight_init="xavier")
    lstm_cls = GravesLSTMLayer if graves else LSTM
    layers = [
        lstm_cls(units=hidden, activation="tanh", backend=backend),
        lstm_cls(units=hidden, activation="tanh", backend=backend),
        RnnOutputLayer(units=vocab_size, activation="softmax", loss="mcxent"),
    ]
    return SequentialConfig(net=net, layers=layers,
                            input_shape=(seq_len, vocab_size))


def alexnet(**kw) -> SequentialModel:
    return SequentialModel(alexnet_config(**kw))


def vgg16(**kw) -> SequentialModel:
    return SequentialModel(vgg16_config(**kw))


def vgg19(**kw) -> SequentialModel:
    return SequentialModel(vgg19_config(**kw))


def simplecnn(**kw) -> SequentialModel:
    return SequentialModel(simplecnn_config(**kw))


def darknet19(**kw) -> SequentialModel:
    return SequentialModel(darknet19_config(**kw))


def text_generation_lstm(**kw) -> SequentialModel:
    return SequentialModel(text_generation_lstm_config(**kw))
