"""Model zoo registry (↔ org.deeplearning4j.zoo.ZooModel + model classes).

The reference zoo couples each architecture with pretrained-weight download;
with zero egress here the registry provides architecture builders only —
weights come from checkpoints via serde/ (↔ ZooModel.initPretrained's role
is played by ModelSerializer.restore).
"""

from __future__ import annotations

from typing import Callable, Dict

from deeplearning4j_tpu.models.lenet import lenet, lenet_config
from deeplearning4j_tpu.models.zoo.classic import (
    alexnet,
    alexnet_config,
    darknet19,
    darknet19_config,
    simplecnn,
    simplecnn_config,
    text_generation_lstm,
    text_generation_lstm_config,
    vgg16,
    vgg16_config,
    vgg19,
    vgg19_config,
)
from deeplearning4j_tpu.models.zoo.graphs import (
    squeezenet,
    squeezenet_config,
    unet,
    unet_config,
    xception,
    xception_config,
)
from deeplearning4j_tpu.models.zoo.resnet import (
    resnet50,
    resnet101,
    resnet152,
    resnet_config,
)
from deeplearning4j_tpu.models.zoo.advanced import (
    inception_resnet_v1,
    inception_resnet_v1_config,
    nasnet,
    nasnet_config,
)
from deeplearning4j_tpu.models.zoo.yolo import (
    Yolo2OutputLayer,
    decode_predictions,
    make_yolo_labels,
    non_max_suppression,
    tiny_yolo,
    tiny_yolo_config,
    yolo2,
    yolo2_config,
)

ZOO: Dict[str, Callable] = {
    "lenet": lenet,
    "alexnet": alexnet,
    "vgg16": vgg16,
    "vgg19": vgg19,
    "simplecnn": simplecnn,
    "darknet19": darknet19,
    "squeezenet": squeezenet,
    "unet": unet,
    "xception": xception,
    "resnet50": resnet50,
    "resnet101": resnet101,
    "resnet152": resnet152,
    "text_generation_lstm": text_generation_lstm,
    "tiny_yolo": tiny_yolo,
    "yolo2": yolo2,
    "inception_resnet_v1": inception_resnet_v1,
    "nasnet": nasnet,
}


def get_model(name: str, **kw):
    """↔ ZooModel lookup by name."""
    try:
        fn = ZOO[name.lower()]
    except KeyError:
        raise KeyError(f"unknown zoo model '{name}'; have {sorted(ZOO)}") from None
    return fn(**kw)


__all__ = ["ZOO", "get_model"] + sorted(
    n for n in dir() if n.endswith("_config") or n in ZOO
)
