"""ResNet family (↔ org.deeplearning4j.zoo.model.ResNet50 — benchmark
config #2 / #5, the north-star conv model).

The reference builds ResNet-50 as a ComputationGraph with explicit
merge/shortcut vertices (zoo ResNet50.graphBuilder: conv/bn/act blocks +
ElementWiseVertex(Add) shortcuts). Here the same DAG is expressed as a
GraphConfig whose whole forward+backward step compiles to ONE XLA program;
residual adds are plain vertices fused by XLA, convs hit the MXU as
conv_general_dilated in NHWC/HWIO layout.

ResNet-v1 bottleneck layout (matches the canonical 50/101/152 definitions):
7x7/2 stem → 3x3/2 maxpool → stages of bottleneck blocks
(1x1 f → 3x3 f → 1x1 4f, projection shortcut on stage entry) →
global avg pool → softmax.
"""

from __future__ import annotations

from typing import Dict, Sequence

from deeplearning4j_tpu.nn.config import (
    GraphConfig,
    GraphVertex,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNorm,
    Conv2D,
    GlobalPooling,
    OutputLayer,
    Pooling2D,
)
from deeplearning4j_tpu.nn.model import GraphModel


def _conv_bn(vertices: Dict[str, GraphVertex], name: str, inp: str, *,
             filters: int, kernel, stride=1, activation: str = "relu",
             padding="SAME") -> str:
    """conv → bn(+act) pair; returns the output vertex name."""
    vertices[f"{name}_conv"] = GraphVertex(
        kind="layer", inputs=[inp],
        layer=Conv2D(filters=filters, kernel=kernel, stride=stride,
                     padding=padding, use_bias=False),
    )
    vertices[f"{name}_bn"] = GraphVertex(
        kind="layer", inputs=[f"{name}_conv"],
        layer=BatchNorm(activation=activation),
    )
    return f"{name}_bn"


def _bottleneck(vertices: Dict[str, GraphVertex], name: str, inp: str, *,
                filters: int, stride: int, project: bool) -> str:
    """1x1 → 3x3 → 1x1(4f) bottleneck with identity/projection shortcut."""
    a = _conv_bn(vertices, f"{name}_a", inp, filters=filters, kernel=1,
                 stride=1)
    b = _conv_bn(vertices, f"{name}_b", a, filters=filters, kernel=3,
                 stride=stride)
    c = _conv_bn(vertices, f"{name}_c", b, filters=4 * filters, kernel=1,
                 stride=1, activation="identity")
    if project:
        short = _conv_bn(vertices, f"{name}_proj", inp, filters=4 * filters,
                         kernel=1, stride=stride, activation="identity")
    else:
        short = inp
    vertices[f"{name}_add"] = GraphVertex(kind="add", inputs=[c, short])
    vertices[f"{name}_relu"] = GraphVertex(
        kind="layer", inputs=[f"{name}_add"], layer=ActivationLayer(activation="relu")
    )
    return f"{name}_relu"


def resnet_config(
    *,
    blocks: Sequence[int] = (3, 4, 6, 3),
    num_classes: int = 1000,
    input_shape=(224, 224, 3),
    updater=None,
    seed: int = 12345,
    dtype: str = "float32",
) -> GraphConfig:
    net = NeuralNetConfiguration(seed=seed, updater=updater, dtype=dtype,
                                 weight_init="relu")
    v: Dict[str, GraphVertex] = {}
    x = _conv_bn(v, "stem", "input", filters=64, kernel=7, stride=2)
    v["stem_pool"] = GraphVertex(
        kind="layer", inputs=[x],
        layer=Pooling2D(pool_type="max", window=3, stride=2, padding="SAME"),
    )
    x = "stem_pool"
    for stage, n_blocks in enumerate(blocks):
        filters = 64 * (2 ** stage)
        for block in range(n_blocks):
            stride = 2 if (stage > 0 and block == 0) else 1
            x = _bottleneck(
                v, f"s{stage}b{block}", x,
                filters=filters, stride=stride, project=(block == 0),
            )
    v["avgpool"] = GraphVertex(
        kind="layer", inputs=[x], layer=GlobalPooling(pool_type="avg")
    )
    v["output"] = GraphVertex(
        kind="layer", inputs=["avgpool"],
        layer=OutputLayer(units=num_classes, activation="softmax", loss="mcxent"),
    )
    return GraphConfig(
        net=net,
        inputs=["input"],
        input_shapes={"input": tuple(input_shape)},
        vertices=v,
        outputs=["output"],
    )


def resnet50(**kw) -> GraphModel:
    return GraphModel(resnet_config(blocks=(3, 4, 6, 3), **kw))


def resnet101(**kw) -> GraphModel:
    return GraphModel(resnet_config(blocks=(3, 4, 23, 3), **kw))


def resnet152(**kw) -> GraphModel:
    return GraphModel(resnet_config(blocks=(3, 8, 36, 3), **kw))
