"""DAG zoo entries: SqueezeNet, U-Net, Xception.

ref: org.deeplearning4j.zoo.model.{SqueezeNet, UNet, Xception} — each a
ComputationGraph in the reference zoo (fire modules / skip concats /
separable-conv residual towers). Built here as GraphConfig DAGs.
"""

from __future__ import annotations

from typing import Dict

from deeplearning4j_tpu.nn.config import (
    GraphConfig,
    GraphVertex,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNorm,
    Conv2D,
    Dropout,
    GlobalPooling,
    LossLayer,
    OutputLayer,
    Pooling2D,
    SeparableConv2D,
    Upsampling2D,
)
from deeplearning4j_tpu.nn.model import GraphModel


def _layer(v: Dict[str, GraphVertex], name: str, inp: str, layer) -> str:
    v[name] = GraphVertex(kind="layer", inputs=[inp], layer=layer)
    return name


# --- SqueezeNet -------------------------------------------------------------


def _fire(v: Dict[str, GraphVertex], name: str, inp: str, *, squeeze: int,
          expand: int) -> str:
    s = _layer(v, f"{name}_sq", inp,
               Conv2D(filters=squeeze, kernel=1, activation="relu"))
    e1 = _layer(v, f"{name}_e1", s,
                Conv2D(filters=expand, kernel=1, activation="relu"))
    e3 = _layer(v, f"{name}_e3", s,
                Conv2D(filters=expand, kernel=3, activation="relu"))
    v[f"{name}_cat"] = GraphVertex(kind="merge", inputs=[e1, e3])
    return f"{name}_cat"


def squeezenet_config(*, num_classes: int = 1000, input_shape=(224, 224, 3),
                      updater=None, seed: int = 12345) -> GraphConfig:
    """↔ zoo SqueezeNet v1.1 (fire modules, conv10 head, global avg pool)."""
    net = NeuralNetConfiguration(seed=seed, updater=updater, weight_init="relu")
    v: Dict[str, GraphVertex] = {}
    x = _layer(v, "stem", "input",
               Conv2D(filters=64, kernel=3, stride=2, activation="relu"))
    x = _layer(v, "pool1", x, Pooling2D(pool_type="max", window=3, stride=2))
    x = _fire(v, "fire2", x, squeeze=16, expand=64)
    x = _fire(v, "fire3", x, squeeze=16, expand=64)
    x = _layer(v, "pool3", x, Pooling2D(pool_type="max", window=3, stride=2))
    x = _fire(v, "fire4", x, squeeze=32, expand=128)
    x = _fire(v, "fire5", x, squeeze=32, expand=128)
    x = _layer(v, "pool5", x, Pooling2D(pool_type="max", window=3, stride=2))
    x = _fire(v, "fire6", x, squeeze=48, expand=192)
    x = _fire(v, "fire7", x, squeeze=48, expand=192)
    x = _fire(v, "fire8", x, squeeze=64, expand=256)
    x = _fire(v, "fire9", x, squeeze=64, expand=256)
    x = _layer(v, "drop9", x, Dropout(rate=0.5))
    x = _layer(v, "conv10", x,
               Conv2D(filters=num_classes, kernel=1, activation="relu"))
    x = _layer(v, "gap", x, GlobalPooling(pool_type="avg"))
    _layer(v, "output", x,
           LossLayer(activation="softmax", loss="mcxent"))
    return GraphConfig(net=net, inputs=["input"],
                       input_shapes={"input": tuple(input_shape)},
                       vertices=v, outputs=["output"])


# --- U-Net ------------------------------------------------------------------


def unet_config(*, num_classes: int = 1, input_shape=(128, 128, 3),
                base_filters: int = 32, depth: int = 4, updater=None,
                seed: int = 12345) -> GraphConfig:
    """↔ zoo UNet (encoder-decoder with skip concats; sigmoid mask head).

    ``num_classes=1`` gives the reference's binary-mask head (sigmoid+xent);
    >1 uses per-pixel softmax.
    """
    net = NeuralNetConfiguration(seed=seed, updater=updater, weight_init="relu")
    v: Dict[str, GraphVertex] = {}

    def double_conv(name, inp, filters):
        a = _layer(v, f"{name}_c1", inp,
                   Conv2D(filters=filters, kernel=3, activation="relu"))
        return _layer(v, f"{name}_c2", a,
                      Conv2D(filters=filters, kernel=3, activation="relu"))

    skips = []
    x = "input"
    for d in range(depth):
        x = double_conv(f"enc{d}", x, base_filters * (2 ** d))
        skips.append(x)
        x = _layer(v, f"down{d}", x, Pooling2D(pool_type="max", window=2))
    x = double_conv("mid", x, base_filters * (2 ** depth))
    for d in reversed(range(depth)):
        x = _layer(v, f"up{d}", x, Upsampling2D(scale=2))
        v[f"cat{d}"] = GraphVertex(kind="merge", inputs=[x, skips[d]])
        x = double_conv(f"dec{d}", f"cat{d}", base_filters * (2 ** d))
    from deeplearning4j_tpu.models.zoo.pixel import PixelOutput

    _layer(v, "output", x, PixelOutput(num_classes=num_classes))
    return GraphConfig(net=net, inputs=["input"],
                       input_shapes={"input": tuple(input_shape)},
                       vertices=v, outputs=["output"])


# --- Xception ---------------------------------------------------------------


def xception_config(*, num_classes: int = 1000, input_shape=(299, 299, 3),
                    updater=None, seed: int = 12345) -> GraphConfig:
    """↔ zoo Xception (entry/middle/exit flows of separable convs with
    residual 1x1-conv shortcuts)."""
    net = NeuralNetConfiguration(seed=seed, updater=updater, weight_init="relu")
    v: Dict[str, GraphVertex] = {}

    def sep_bn(name, inp, filters, activation_first=True):
        src = inp
        if activation_first:
            src = _layer(v, f"{name}_act", src, ActivationLayer(activation="relu"))
        c = _layer(v, f"{name}_sep", src,
                   SeparableConv2D(filters=filters, kernel=3, use_bias=False))
        return _layer(v, f"{name}_bn", c, BatchNorm())

    def conv_bn(name, inp, filters, kernel, stride):
        c = _layer(v, f"{name}_conv", inp,
                   Conv2D(filters=filters, kernel=kernel, stride=stride,
                          use_bias=False))
        return _layer(v, f"{name}_bn", c, BatchNorm(activation="relu"))

    x = conv_bn("stem1", "input", 32, 3, 2)
    x = conv_bn("stem2", x, 64, 3, 1)

    def entry_block(name, inp, filters, first_act=True):
        a = sep_bn(f"{name}_s1", inp, filters, activation_first=first_act)
        b = sep_bn(f"{name}_s2", a, filters)
        p = _layer(v, f"{name}_pool", b,
                   Pooling2D(pool_type="max", window=3, stride=2, padding="SAME"))
        sc = _layer(v, f"{name}_short", inp,
                    Conv2D(filters=filters, kernel=1, stride=2, use_bias=False))
        sb = _layer(v, f"{name}_shortbn", sc, BatchNorm())
        v[f"{name}_add"] = GraphVertex(kind="add", inputs=[p, sb])
        return f"{name}_add"

    x = entry_block("e1", x, 128, first_act=False)
    x = entry_block("e2", x, 256)
    x = entry_block("e3", x, 728)

    for i in range(8):
        inp = x
        a = sep_bn(f"m{i}_s1", inp, 728)
        b = sep_bn(f"m{i}_s2", a, 728)
        c = sep_bn(f"m{i}_s3", b, 728)
        v[f"m{i}_add"] = GraphVertex(kind="add", inputs=[c, inp])
        x = f"m{i}_add"

    a = sep_bn("x1_s1", x, 728)
    b = sep_bn("x1_s2", a, 1024)
    p = _layer(v, "x1_pool", b,
               Pooling2D(pool_type="max", window=3, stride=2, padding="SAME"))
    sc = _layer(v, "x1_short", x,
                Conv2D(filters=1024, kernel=1, stride=2, use_bias=False))
    sb = _layer(v, "x1_shortbn", sc, BatchNorm())
    v["x1_add"] = GraphVertex(kind="add", inputs=[p, sb])
    c = _layer(v, "x2_sep", "x1_add",
               SeparableConv2D(filters=1536, kernel=3, use_bias=False))
    c = _layer(v, "x2_bn", c, BatchNorm(activation="relu"))
    c = _layer(v, "x3_sep", c,
               SeparableConv2D(filters=2048, kernel=3, use_bias=False))
    c = _layer(v, "x3_bn", c, BatchNorm(activation="relu"))
    g = _layer(v, "gap", c, GlobalPooling(pool_type="avg"))
    _layer(v, "output", g,
           OutputLayer(units=num_classes, activation="softmax", loss="mcxent"))
    return GraphConfig(net=net, inputs=["input"],
                       input_shapes={"input": tuple(input_shape)},
                       vertices=v, outputs=["output"])


def squeezenet(**kw) -> GraphModel:
    return GraphModel(squeezenet_config(**kw))


def unet(**kw) -> GraphModel:
    return GraphModel(unet_config(**kw))


def xception(**kw) -> GraphModel:
    return GraphModel(xception_config(**kw))
