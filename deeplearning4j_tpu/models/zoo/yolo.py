"""YOLOv2 object detection family (↔ org.deeplearning4j.zoo.model.{TinyYOLO,
YOLO2} + org.deeplearning4j.nn.layers.objdetect.{Yolo2OutputLayer, YoloUtils}).

TPU-first redesign of the reference's output layer:

* The reference's label format is a [N, 4+C, H, W] channel-first tensor with
  corner-coordinate boxes, decoded object-by-object on the host. Here labels
  are a dense NHWC grid ``[N, gridH, gridW, 5+C]`` per cell:
  ``(objectness, x, y, w, h, class one-hot)`` with x/y cell-relative in
  [0,1] and w/h in grid units — one responsible object per cell (the YOLOv2
  assumption). Everything in the loss is static-shape tensor algebra: the
  responsible anchor per object cell is an argmax over shape-IOU with the
  anchor priors, exactly darknet's rule, with no dynamic gather.
* Box decode + NMS (``YoloUtils.getPredictedObjects`` role) are
  jit-compatible: top-K via ``lax.top_k`` and a fixed-iteration NMS sweep —
  no data-dependent shapes, so detection post-processing can run on-device.

Loss terms follow YOLOv2: coord MSE (λ=5) on cell-relative xy and √wh of
the responsible anchor, objectness MSE toward the live IOU, no-object
confidence suppression (λ=0.5) outside a responsible anchor, and per-cell
class cross-entropy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.config import (
    GraphConfig,
    GraphVertex,
    LayerConfig,
    NeuralNetConfiguration,
    SequentialConfig,
    register_config,
)
from deeplearning4j_tpu.nn.layers import (
    BatchNorm,
    Conv2D,
    Pooling2D,
    SpaceToDepth,
)
from deeplearning4j_tpu.nn.model import GraphModel, SequentialModel

# anchor priors in grid units (↔ the reference zoo models' priorBoxes)
TINY_YOLO_ANCHORS = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38),
                     (9.42, 5.11), (16.62, 10.52))
YOLO2_ANCHORS = ((0.57273, 0.677385), (1.87446, 2.06253), (3.33843, 5.47434),
                 (7.88282, 3.52778), (9.77052, 9.16828))


def _shape_iou(wh_a, wh_b):
    """IOU of boxes sharing a center — darknet's anchor-assignment rule."""
    inter = jnp.minimum(wh_a[..., 0], wh_b[..., 0]) * \
        jnp.minimum(wh_a[..., 1], wh_b[..., 1])
    union = wh_a[..., 0] * wh_a[..., 1] + wh_b[..., 0] * wh_b[..., 1] - inter
    return inter / jnp.maximum(union, 1e-9)


def _box_iou(xy_a, wh_a, xy_b, wh_b):
    """IOU of center-format boxes (same units both sides)."""
    lo = jnp.maximum(xy_a - wh_a / 2, xy_b - wh_b / 2)
    hi = jnp.minimum(xy_a + wh_a / 2, xy_b + wh_b / 2)
    inter = jnp.prod(jnp.clip(hi - lo, 0.0), axis=-1)
    union = (wh_a[..., 0] * wh_a[..., 1] + wh_b[..., 0] * wh_b[..., 1]
             - inter)
    return inter / jnp.maximum(union, 1e-9)


@register_config
@dataclass
class Yolo2OutputLayer(LayerConfig):
    """↔ org.deeplearning4j.nn.layers.objdetect.Yolo2OutputLayer.

    Consumes a feature map ``[N, H, W, B*(5+C)]`` (B = len(anchors)).
    ``apply`` returns decoded ``(xy, wh, conf, class_probs)`` concatenated
    as ``[N, H, W, B, 5+C]``; ``compute_loss`` takes the dense grid labels
    described in the module docstring.
    """

    anchors: Sequence[Tuple[float, float]] = field(
        default_factory=lambda: TINY_YOLO_ANCHORS)
    num_classes: int = 20
    lambda_coord: float = 5.0
    lambda_noobj: float = 0.5

    @property
    def has_params(self):
        return False

    def output_shape(self, input_shape):
        h, w, c = input_shape
        b = len(self.anchors)
        assert c == b * (5 + self.num_classes), (
            f"feature channels {c} != {b}*(5+{self.num_classes})")
        return (h, w, b, 5 + self.num_classes)

    def init(self, rng, input_shape, dtype):
        return {}, {}

    def _split(self, x):
        n, h, w, c = x.shape
        b = len(self.anchors)
        x = x.reshape(n, h, w, b, 5 + self.num_classes)
        txy, twh, to, tc = (x[..., 0:2], x[..., 2:4], x[..., 4],
                            x[..., 5:])
        anchors = jnp.asarray(self.anchors, x.dtype)
        xy = jax.nn.sigmoid(txy)                       # cell-relative
        wh = anchors * jnp.exp(jnp.clip(twh, -8, 8))   # grid units
        return xy, wh, to, tc

    def apply(self, params, state, x, *, train=False, rng=None):
        xy, wh, to, tc = self._split(x)
        out = jnp.concatenate(
            [xy, wh, jax.nn.sigmoid(to)[..., None], jax.nn.softmax(tc, -1)],
            axis=-1)
        return out, state

    def compute_loss(self, params, state, x, labels, *, mask=None,
                     weights=None):
        xy, wh, to, tc = self._split(x)        # [N,H,W,B,*]
        obj = labels[..., 0]                   # [N,H,W]
        txy = labels[..., 1:3]                 # cell-relative target
        twh = labels[..., 3:5]                 # grid-unit target
        tcls = labels[..., 5:]

        anchors = jnp.asarray(self.anchors, x.dtype)   # [B,2]
        # responsible anchor per object cell: best shape-IOU vs priors
        prior_iou = _shape_iou(anchors[None, None, None, :, :],
                               twh[..., None, :])      # [N,H,W,B]
        resp = jax.nn.one_hot(jnp.argmax(prior_iou, -1), len(self.anchors),
                              dtype=x.dtype)           # [N,H,W,B]
        resp = resp * obj[..., None]

        # live IOU of each predicted box vs the cell's target (same units)
        live_iou = _box_iou(xy, wh, txy[..., None, :], twh[..., None, :])

        sum_img = lambda a: jnp.sum(a, axis=tuple(range(1, a.ndim)))  # noqa: E731
        coord = sum_img(resp[..., None] * (
            jnp.square(xy - txy[..., None, :])
            + jnp.square(jnp.sqrt(jnp.maximum(wh, 1e-9))
                         - jnp.sqrt(jnp.maximum(twh[..., None, :], 1e-9)))))
        conf_obj = sum_img(resp * jnp.square(jax.nn.sigmoid(to)
                                             - jax.lax.stop_gradient(live_iou)))
        conf_noobj = sum_img((1.0 - resp) * jnp.square(jax.nn.sigmoid(to)))
        logp = jax.nn.log_softmax(tc, -1)
        cls = -sum_img(resp[..., None] * tcls[..., None, :] * logp)

        per_image = (self.lambda_coord * coord + conf_obj
                     + self.lambda_noobj * conf_noobj + cls)   # [N]
        w = mask if mask is not None else weights
        if w is not None:
            w = jnp.asarray(w, per_image.dtype).reshape(per_image.shape)
            return jnp.sum(per_image * w) / jnp.maximum(jnp.sum(w), 1e-12)
        return jnp.mean(per_image)


def decode_predictions(decoded, *, top_k: int = 20):
    """↔ YoloUtils.getPredictedObjects, jit-compatible.

    decoded: Yolo2OutputLayer.apply output [N,H,W,B,5+C]. Returns
    (boxes [N,K,4] as (x1,y1,x2,y2) in [0,1] image coords, scores [N,K],
    classes [N,K] int32), top-K by confidence*class score.
    """
    n, h, w, b, _ = decoded.shape
    top_k = min(top_k, h * w * b)
    xy, wh = decoded[..., 0:2], decoded[..., 2:4]
    conf, probs = decoded[..., 4], decoded[..., 5:]
    cols = jnp.arange(w, dtype=decoded.dtype)
    rows = jnp.arange(h, dtype=decoded.dtype)
    cx = (xy[..., 0] + cols[None, None, :, None]) / w
    cy = (xy[..., 1] + rows[None, :, None, None]) / h
    bw = wh[..., 0] / w
    bh = wh[..., 1] / h
    cls_score = jnp.max(probs, -1) * conf
    cls_id = jnp.argmax(probs, -1)

    flat = lambda a: a.reshape(n, h * w * b)  # noqa: E731
    scores, idx = jax.lax.top_k(flat(cls_score), top_k)
    take = lambda a: jnp.take_along_axis(flat(a), idx, axis=1)  # noqa: E731
    x1 = take(cx) - take(bw) / 2
    y1 = take(cy) - take(bh) / 2
    x2 = take(cx) + take(bw) / 2
    y2 = take(cy) + take(bh) / 2
    boxes = jnp.stack([x1, y1, x2, y2], -1)
    return boxes, scores, jnp.take_along_axis(flat(cls_id), idx, axis=1)


def non_max_suppression(boxes, scores, *, iou_threshold: float = 0.45):
    """Fixed-iteration NMS over top-K candidates (static shapes, vmappable).

    Returns ``keep`` [N,K] {0,1}: greedy suppression in score order — for
    each candidate in descending-score order, drop it if it overlaps an
    already-kept higher-scoring box above the threshold.
    """

    def one_image(bx, sc):
        k = bx.shape[0]
        order = jnp.argsort(-sc)
        bx = bx[order]

        x1, y1, x2, y2 = bx[:, 0], bx[:, 1], bx[:, 2], bx[:, 3]
        area = jnp.clip(x2 - x1, 0) * jnp.clip(y2 - y1, 0)
        ix1 = jnp.maximum(x1[:, None], x1[None, :])
        iy1 = jnp.maximum(y1[:, None], y1[None, :])
        ix2 = jnp.minimum(x2[:, None], x2[None, :])
        iy2 = jnp.minimum(y2[:, None], y2[None, :])
        inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
        iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-9)

        def body(i, keep):
            # suppressed iff any kept earlier box overlaps too much
            over = (iou[i] > iou_threshold) & (jnp.arange(k) < i) & (keep > 0)
            return keep.at[i].set(jnp.where(jnp.any(over), 0.0, 1.0))

        keep_sorted = jax.lax.fori_loop(0, k, body, jnp.ones((k,), bx.dtype))
        inv = jnp.zeros_like(order).at[order].set(jnp.arange(k))
        return keep_sorted[inv]

    return jax.vmap(one_image)(boxes, scores)


# --- zoo entries ------------------------------------------------------------


def _cbl(filters, kernel):
    return [Conv2D(filters=filters, kernel=kernel, use_bias=False),
            BatchNorm(activation="leakyrelu")]


def tiny_yolo_config(*, num_classes: int = 20, input_shape=(416, 416, 3),
                     anchors=TINY_YOLO_ANCHORS, updater=None,
                     seed: int = 12345) -> SequentialConfig:
    """↔ zoo TinyYOLO: 9-conv darknet-tiny backbone, stride 32, B=5."""
    net = NeuralNetConfiguration(seed=seed, updater=updater, weight_init="relu")
    b = len(anchors)
    layers = []
    for filters in (16, 32, 64, 128, 256):
        layers += _cbl(filters, 3) + [Pooling2D(pool_type="max", window=2)]
    layers += _cbl(512, 3)
    layers += _cbl(1024, 3) + _cbl(1024, 3)
    layers += [Conv2D(filters=b * (5 + num_classes), kernel=1),
               Yolo2OutputLayer(anchors=tuple(anchors),
                                num_classes=num_classes)]
    return SequentialConfig(net=net, layers=layers, input_shape=input_shape)


def tiny_yolo(**kw) -> SequentialModel:
    return SequentialModel(tiny_yolo_config(**kw))


def yolo2_config(*, num_classes: int = 80, input_shape=(608, 608, 3),
                 anchors=YOLO2_ANCHORS, updater=None,
                 seed: int = 12345) -> GraphConfig:
    """↔ zoo YOLO2: darknet19 backbone + reorg passthrough (the 26x26
    stage is space-to-depth'd and concatenated with the 13x13 head)."""
    net = NeuralNetConfiguration(seed=seed, updater=updater, weight_init="relu")
    b = len(anchors)
    v = {}
    x = "input"

    def add(name, layer, inp):
        v[name] = GraphVertex(kind="layer", inputs=[inp], layer=layer)
        return name

    def cbl(name, inp, filters, kernel):
        c = add(f"{name}_c", Conv2D(filters=filters, kernel=kernel,
                                    use_bias=False), inp)
        return add(f"{name}_bn", BatchNorm(activation="leakyrelu"), c)

    def pool(name, inp):
        return add(name, Pooling2D(pool_type="max", window=2), inp)

    x = cbl("s1", x, 32, 3)
    x = pool("p1", x)
    x = cbl("s2", x, 64, 3)
    x = pool("p2", x)
    for i, f in enumerate((128, 64, 128)):
        x = cbl(f"s3_{i}", x, f, 3 if f == 128 else 1)
    x = pool("p3", x)
    for i, f in enumerate((256, 128, 256)):
        x = cbl(f"s4_{i}", x, f, 3 if f == 256 else 1)
    x = pool("p4", x)
    for i, f in enumerate((512, 256, 512, 256, 512)):
        x = cbl(f"s5_{i}", x, f, 3 if f == 512 else 1)
    passthrough = x                      # 26x26x512 stage
    x = pool("p5", x)
    for i, f in enumerate((1024, 512, 1024, 512, 1024)):
        x = cbl(f"s6_{i}", x, f, 3 if f == 1024 else 1)
    x = cbl("head1", x, 1024, 3)
    x = cbl("head2", x, 1024, 3)

    reorg = add("reorg", SpaceToDepth(block_size=2), passthrough)
    v["route"] = GraphVertex(kind="merge", inputs=[reorg, x])
    x = cbl("head3", "route", 1024, 3)
    x = add("head_out", Conv2D(filters=b * (5 + num_classes), kernel=1), x)
    v["yolo"] = GraphVertex(
        kind="layer", inputs=[x],
        layer=Yolo2OutputLayer(anchors=tuple(anchors),
                               num_classes=num_classes))
    return GraphConfig(net=net, inputs=["input"],
                       input_shapes={"input": tuple(input_shape)},
                       vertices=v, outputs=["yolo"])


def yolo2(**kw) -> GraphModel:
    return GraphModel(yolo2_config(**kw))


def make_yolo_labels(objects: List[List[Tuple[float, float, float, float, int]]],
                     *, grid: Tuple[int, int], num_classes: int) -> np.ndarray:
    """Host-side label builder: per image a list of (cx, cy, w, h, cls) in
    [0,1] image coords → dense [N, gridH, gridW, 5+C] grid labels."""
    gh, gw = grid
    n = len(objects)
    out = np.zeros((n, gh, gw, 5 + num_classes), np.float32)
    for i, objs in enumerate(objects):
        for (cx, cy, w, h, cls) in objs:
            col = min(int(cx * gw), gw - 1)
            row = min(int(cy * gh), gh - 1)
            out[i, row, col, 0] = 1.0
            out[i, row, col, 1] = cx * gw - col
            out[i, row, col, 2] = cy * gh - row
            out[i, row, col, 3] = w * gw
            out[i, row, col, 4] = h * gh
            out[i, row, col, 5 + cls] = 1.0
    return out
