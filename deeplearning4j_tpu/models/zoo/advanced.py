"""Advanced zoo DAGs: Inception-ResNet-V1 and NASNet-A
(↔ org.deeplearning4j.zoo.model.{InceptionResNetV1 (FaceNet backbone),
NASNet}).

Both are GraphConfig DAGs like graphs.py. Block structure follows the
papers the reference zoo implements (Szegedy et al. 2016 Inception-ResNet;
Zoph et al. 2018 NASNet-A): scaled residual inception branches, and
NASNet's two-input cells (h, h_prev) of separable-conv/pool/identity pairs.
Filter counts are parametric so convergence tests run at reduced width.
"""

from __future__ import annotations

from typing import Dict, Optional

from deeplearning4j_tpu.nn.config import (
    GraphConfig,
    GraphVertex,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    GlobalPooling,
    OutputLayer,
    Pooling2D,
    SeparableConv2D,
)
from deeplearning4j_tpu.nn.model import GraphModel


from deeplearning4j_tpu.models.zoo.graphs import _layer  # shared helper


def _merge(v, name, inputs, kind="merge"):
    v[name] = GraphVertex(kind=kind, inputs=list(inputs))
    return name


def _scaled_residual(v, name, inp, branch, factor):
    """x + factor * branch — the Inception-ResNet residual scaling."""
    v[f"{name}_scl"] = GraphVertex(kind="scale", inputs=[branch],
                                   args={"factor": factor})
    return _merge(v, name, [inp, f"{name}_scl"], kind="add")


def _cb(v, name, inp, filters, kernel, stride=1, *, act="relu",
        padding="SAME"):
    c = _layer(v, f"{name}_c", inp,
               Conv2D(filters=filters, kernel=kernel, stride=stride,
                      padding=padding, use_bias=False))
    return _layer(v, f"{name}_bn", c, BatchNorm(activation=act))


# --- Inception-ResNet-V1 ----------------------------------------------------


def _ir_block_a(v, name, inp, w):
    """35x35 Inception-ResNet-A: 1x1 / 1x1-3x3 / 1x1-3x3-3x3 branches,
    1x1 projection, scaled residual add."""
    b0 = _cb(v, f"{name}_b0", inp, w, 1)
    b1 = _cb(v, f"{name}_b1b", _cb(v, f"{name}_b1a", inp, w, 1), w, 3)
    b2a = _cb(v, f"{name}_b2a", inp, w, 1)
    b2 = _cb(v, f"{name}_b2c", _cb(v, f"{name}_b2b", b2a, w, 3), w, 3)
    cat = _merge(v, f"{name}_cat", [b0, b1, b2])
    up = _layer(v, f"{name}_up", cat,
                Conv2D(filters=4 * w, kernel=1))  # linear projection
    add = _scaled_residual(v, f"{name}_add", inp, up, 0.17)
    return _layer(v, f"{name}_relu", add, ActivationLayer(activation="relu"))


def _ir_block_b(v, name, inp, w, channels):
    """17x17 Inception-ResNet-B: 1x1 / 1x1-1x7-7x1 branches."""
    b0 = _cb(v, f"{name}_b0", inp, w, 1)
    b1a = _cb(v, f"{name}_b1a", inp, w, 1)
    b1b = _cb(v, f"{name}_b1b", b1a, w, (1, 7))
    b1 = _cb(v, f"{name}_b1c", b1b, w, (7, 1))
    cat = _merge(v, f"{name}_cat", [b0, b1])
    up = _layer(v, f"{name}_up", cat, Conv2D(filters=channels, kernel=1))
    add = _scaled_residual(v, f"{name}_add", inp, up, 0.10)
    return _layer(v, f"{name}_relu", add, ActivationLayer(activation="relu"))


def _ir_reduction_a(v, name, inp, w):
    p = _layer(v, f"{name}_pool", inp,
               Pooling2D(pool_type="max", window=3, stride=2, padding="SAME"))
    c = _cb(v, f"{name}_c", inp, 2 * w, 3, stride=2)
    d = _cb(v, f"{name}_d2", _cb(v, f"{name}_d1", inp, w, 1), 2 * w, 3,
            stride=2)
    return _merge(v, f"{name}_cat", [p, c, d])


def inception_resnet_v1_config(
    *, num_classes: int = 0, embedding: int = 128, width: int = 32,
    blocks_a: int = 3, blocks_b: int = 5, input_shape=(160, 160, 3),
    updater=None, dropout: float = 0.2, seed: int = 12345,
) -> GraphConfig:
    """↔ zoo InceptionResNetV1 (FaceNet): stem → A-blocks → Reduction-A →
    B-blocks → pooled bottleneck embedding (num_classes=0) or softmax head.
    Width/blocks are parametric (reference: width 32, 5×A, 10×B + C tower).
    """
    net = NeuralNetConfiguration(seed=seed, updater=updater,
                                 weight_init="relu")
    v: Dict[str, GraphVertex] = {}
    w = width

    x = _cb(v, "stem1", "input", w, 3, stride=2)
    x = _cb(v, "stem2", x, w, 3)
    x = _cb(v, "stem3", x, 2 * w, 3)
    x = _layer(v, "stem_pool", x,
               Pooling2D(pool_type="max", window=3, stride=2, padding="SAME"))
    x = _cb(v, "stem4", x, 2 * w + w // 2, 1)
    x = _cb(v, "stem5", x, 4 * w, 3)
    # channels entering the A tower must equal the A-block projection (4w)
    for i in range(blocks_a):
        x = _ir_block_a(v, f"a{i}", x, w)
    x = _ir_reduction_a(v, "red_a", x, 4 * w)
    channels_b = 4 * w + 2 * (2 * 4 * w)  # pool + conv + double-conv branches
    for i in range(blocks_b):
        x = _ir_block_b(v, f"b{i}", x, 2 * w, channels_b)

    x = _layer(v, "avgpool", x, GlobalPooling(pool_type="avg"))
    if dropout:
        x = _layer(v, "drop", x, Dropout(rate=dropout))
    if num_classes:
        v["output"] = GraphVertex(
            kind="layer", inputs=[x],
            layer=OutputLayer(units=num_classes, activation="softmax",
                              loss="mcxent"))
        outputs = ["output"]
    else:
        # FaceNet bottleneck: linear embedding (L2-normalized by callers).
        # Inference/transfer surface only — to TRAIN, build with a softmax
        # head (num_classes=N) and strip it afterward, the same recipe the
        # reference's FaceNet path uses (GraphModel.loss_fn rejects this
        # head with a clear error if fit directly).
        x = _layer(v, "bottleneck", x, Dense(units=embedding,
                                             activation="identity"))
        outputs = [x]
    return GraphConfig(net=net, inputs=["input"],
                       input_shapes={"input": tuple(input_shape)},
                       vertices=v, outputs=outputs)


def inception_resnet_v1(**kw) -> GraphModel:
    return GraphModel(inception_resnet_v1_config(**kw))


# --- NASNet-A ---------------------------------------------------------------


def _sep_block(v, name, inp, filters, kernel, stride=1):
    """NASNet separable block: relu → sepconv → bn, twice (stride on 1st)."""
    a = _layer(v, f"{name}_r1", inp, ActivationLayer(activation="relu"))
    a = _layer(v, f"{name}_s1", a,
               SeparableConv2D(filters=filters, kernel=kernel, stride=stride,
                               padding="SAME", use_bias=False))
    a = _layer(v, f"{name}_bn1", a, BatchNorm())
    b = _layer(v, f"{name}_r2", a, ActivationLayer(activation="relu"))
    b = _layer(v, f"{name}_s2", b,
               SeparableConv2D(filters=filters, kernel=kernel, stride=1,
                               padding="SAME", use_bias=False))
    return _layer(v, f"{name}_bn2", b, BatchNorm())


def _fit(v, name, inp, filters, stride=1):
    """1x1 (optionally strided) projection so cell inputs agree in
    shape/width (the role of NASNet's squeeze/adjust blocks)."""
    a = _layer(v, f"{name}_r", inp, ActivationLayer(activation="relu"))
    a = _layer(v, f"{name}_c", a,
               Conv2D(filters=filters, kernel=1, stride=stride,
                      use_bias=False))
    return _layer(v, f"{name}_bn", a, BatchNorm())


def _normal_cell(v, name, h, h_prev, filters):
    """NASNet-A normal cell: 5 pairwise-add blocks over (h, h_prev)."""
    h = _fit(v, f"{name}_fit_h", h, filters)
    p = _fit(v, f"{name}_fit_p", h_prev, filters)
    b1 = _merge(v, f"{name}_b1", [
        _sep_block(v, f"{name}_b1l", h, filters, 3), h], kind="add")
    b2 = _merge(v, f"{name}_b2", [
        _sep_block(v, f"{name}_b2l", p, filters, 3),
        _sep_block(v, f"{name}_b2r", h, filters, 5)], kind="add")
    b3 = _merge(v, f"{name}_b3", [
        _layer(v, f"{name}_b3l", p,
               Pooling2D(pool_type="avg", window=3, stride=1,
                         padding="SAME")), p], kind="add")
    b4 = _merge(v, f"{name}_b4", [
        _sep_block(v, f"{name}_b4l", p, filters, 5),
        _sep_block(v, f"{name}_b4r", p, filters, 3)], kind="add")
    b5 = _merge(v, f"{name}_b5", [
        _layer(v, f"{name}_b5l", h,
               Pooling2D(pool_type="avg", window=3, stride=1,
                         padding="SAME")), h], kind="add")
    out = _merge(v, f"{name}_out", [b1, b2, b3, b4, b5])
    return out, h  # (cell output, new h_prev)


def _reduction_cell(v, name, h, h_prev, filters):
    """NASNet-A reduction cell: h_prev feeds the sep7x7/sep5x5 right-hand
    branches of blocks 1-3 (paper topology), everything strided to /2."""
    h = _fit(v, f"{name}_fit_h", h, filters)
    p = _fit(v, f"{name}_fit_p", h_prev, filters)
    b1 = _merge(v, f"{name}_b1", [
        _sep_block(v, f"{name}_b1l", h, filters, 5, stride=2),
        _sep_block(v, f"{name}_b1r", p, filters, 7, stride=2)], kind="add")
    b2 = _merge(v, f"{name}_b2", [
        _layer(v, f"{name}_b2l", h,
               Pooling2D(pool_type="max", window=3, stride=2,
                         padding="SAME")),
        _sep_block(v, f"{name}_b2r", p, filters, 7, stride=2)], kind="add")
    b3 = _merge(v, f"{name}_b3", [
        _layer(v, f"{name}_b3l", h,
               Pooling2D(pool_type="avg", window=3, stride=2,
                         padding="SAME")),
        _sep_block(v, f"{name}_b3r", p, filters, 5, stride=2)], kind="add")
    b4 = _merge(v, f"{name}_b4", [
        _layer(v, f"{name}_b4l", b1,
               Pooling2D(pool_type="max", window=3, stride=1,
                         padding="SAME")), b2], kind="add")
    out = _merge(v, f"{name}_out", [b1, b3, b4])
    # next cell's h_prev is this cell's strided h (shape-compatible)
    hp = _fit(v, f"{name}_fit_hp", h, filters, stride=2)
    return out, hp


def nasnet_config(*, num_classes: int = 1000, input_shape=(224, 224, 3),
                  penultimate_filters: int = 176, cells_per_stack: int = 2,
                  stem_filters: int = 32, updater=None, dropout: float = 0.5,
                  seed: int = 12345) -> GraphConfig:
    """↔ zoo NASNet (NASNet-A). The mobile reference config is
    penultimate_filters=1056, cells_per_stack=4, stem 32; defaults here are
    narrower for single-host training, same cell topology."""
    net = NeuralNetConfiguration(seed=seed, updater=updater,
                                 weight_init="relu")
    v: Dict[str, GraphVertex] = {}
    f = penultimate_filters // 24  # NASNet filter-scaling convention

    x = _cb(v, "stem", "input", stem_filters, 3, stride=2, act="identity")
    h, p = x, x
    for i in range(cells_per_stack):
        h, p = _normal_cell(v, f"n1_{i}", h, p, f)
    h, p = _reduction_cell(v, "r1", h, p, 2 * f)
    for i in range(cells_per_stack):
        h, p = _normal_cell(v, f"n2_{i}", h, p, 2 * f)
    h, p = _reduction_cell(v, "r2", h, p, 4 * f)
    for i in range(cells_per_stack):
        h, p = _normal_cell(v, f"n3_{i}", h, p, 4 * f)

    x = _layer(v, "final_relu", h, ActivationLayer(activation="relu"))
    x = _layer(v, "gap", x, GlobalPooling(pool_type="avg"))
    if dropout:
        x = _layer(v, "drop", x, Dropout(rate=dropout))
    v["output"] = GraphVertex(
        kind="layer", inputs=[x],
        layer=OutputLayer(units=num_classes, activation="softmax",
                          loss="mcxent"))
    return GraphConfig(net=net, inputs=["input"],
                       input_shapes={"input": tuple(input_shape)},
                       vertices=v, outputs=["output"])


def nasnet(**kw) -> GraphModel:
    return GraphModel(nasnet_config(**kw))
