"""Per-pixel output head for segmentation models (↔ the reference UNet's
final 1x1-conv + sigmoid/xent CnnLossLayer combination)."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from deeplearning4j_tpu.nn.config import LayerConfig, register_config
from deeplearning4j_tpu.nn.initializers import get_initializer
from deeplearning4j_tpu.ops import cnn as opscnn
from deeplearning4j_tpu.ops import loss as losses
from deeplearning4j_tpu.ops import nn as opsnn


@register_config
@dataclass
class PixelOutput(LayerConfig):
    """1x1 conv to ``num_classes`` channels + per-pixel loss.

    num_classes == 1 → sigmoid / binary cross-entropy (mask prediction);
    num_classes  > 1 → softmax cross-entropy over the channel axis.
    Labels: [N,H,W,1] binary mask or [N,H,W,C] one-hot.
    """

    num_classes: int = 1

    def output_shape(self, input_shape):
        h, w, _ = input_shape
        return (h, w, self.num_classes)

    def init(self, rng, input_shape, dtype):
        c = input_shape[-1]
        w_init = get_initializer("xavier")
        return {
            "W": w_init(rng, (1, 1, c, self.num_classes), dtype),
            "b": jnp.zeros((self.num_classes,), dtype),
        }, {}

    def _logits(self, params, x):
        return opscnn.conv2d(x, params["W"], params.get("b"), stride=1,
                             padding="SAME")

    def apply(self, params, state, x, *, train=False, rng=None):
        logits = self._logits(params, x)
        if self.num_classes == 1:
            return opsnn.sigmoid(logits), state
        return opsnn.softmax(logits), state

    def compute_loss(self, params, state, x, labels, *, mask=None, weights=None):
        logits = self._logits(params, x)
        if self.num_classes == 1:
            y = labels if labels.ndim == 4 else labels[..., None]
            per = losses.binary_cross_entropy(logits, y, reduction="none")
        else:
            per = losses.softmax_cross_entropy(logits, labels, reduction="none")
        if mask is not None:
            per = per * mask
            return jnp.sum(per) / jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.mean(per)
