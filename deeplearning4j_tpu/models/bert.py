"""BERT model family (north-star workload #4).

ref: the reference runs BERT-base by importing a TF frozen graph into
SameDiff and interpreting it op-by-op (SURVEY §3.2). Here BERT is a native
model: one traced function → one XLA program per step. Masked-LM + NSP
pretraining heads included; the encoder is a stack of
TransformerEncoderBlock (Pallas flash attention inside).

Batch convention (all host-built, static shapes):
    features = {"token_ids": [N,T] int32, "segment_ids": [N,T] int32,
                "mask": [N,T] 1/0 float}
    labels   = {"mlm_labels": [N,T] int32 (original ids at masked slots),
                "mlm_mask":   [N,T] 1/0 float (which slots are masked),
                "nsp": [N] int32 (optional next-sentence label)}

MLM loss supports two equivalent batch layouts:

* dense — loss over *all* positions weighted by ``mlm_mask`` [N,T];
* gathered — the batch additionally carries ``mlm_positions`` [N,P] int32,
  ``mlm_weights`` [N,P] and position-indexed ``mlm_labels`` [N,P], with P a
  FIXED max-predictions count (static shapes; padded slots weight 0). The
  decoder matmul then runs over [N,P,H] instead of [N,T,H] — at the
  standard mask rate P ≈ 0.15·T, cutting the vocab-size GEMM ~6x with
  bit-identical loss semantics (only masked slots ever contribute). This is
  the layout the reference's TF BERT graph itself uses
  (gather_indexes + label_weights in the masked-LM head).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration, register_config
from deeplearning4j_tpu.nn.layers.attention import TransformerEncoderBlock
from deeplearning4j_tpu.ops import loss as losses
from deeplearning4j_tpu.ops import nn as opsnn
from deeplearning4j_tpu.train.updaters import Adam


@register_config
@dataclass
class BertConfig:
    """Architecture config (JSON round-trip via the config registry)."""

    vocab_size: int = 30522
    hidden: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate: int = 3072
    max_position: int = 512
    type_vocab: int = 2
    dropout: float = 0.1
    attention_dropout: float = 0.1
    activation: str = "gelu"
    eps: float = 1e-12
    use_nsp: bool = True
    initializer_range: float = 0.02
    # jax.checkpoint each encoder block (recompute-in-backward): the
    # memory lever for long-context / deep configs — see
    # TransformerEncoderBlock.remat.
    remat: bool = False
    net: NeuralNetConfiguration = field(
        default_factory=lambda: NeuralNetConfiguration(updater=Adam(1e-4))
    )


class Bert:
    """BERT encoder + MLM/NSP pretraining heads.

    Same model protocol as SequentialModel/GraphModel: ``init`` →
    variables pytree, ``apply``/``loss_fn`` pure (Trainer-compatible).
    """

    def __init__(self, config: BertConfig):
        self.config = config
        self.net = config.net
        self._block = TransformerEncoderBlock(
            num_heads=config.num_heads,
            intermediate=config.intermediate,
            activation=config.activation,
            dropout=config.dropout,
            attention_dropout=config.attention_dropout,
            post_ln=True,
            eps=config.eps,
            remat=config.remat,
        )

    # -- construction ------------------------------------------------------

    def init(self, seed: Optional[int] = None) -> Dict[str, Any]:
        c = self.config
        seed = self.net.seed if seed is None else seed
        rng = jax.random.key(seed)
        dtype = jnp.dtype(self.net.dtype)
        std = c.initializer_range

        def trunc(key, shape):
            return std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)

        ks = jax.random.split(rng, 8 + c.num_layers)
        params: Dict[str, Any] = {
            "embeddings": {
                "word": trunc(ks[0], (c.vocab_size, c.hidden)),
                "position": trunc(ks[1], (c.max_position, c.hidden)),
                "type": trunc(ks[2], (c.type_vocab, c.hidden)),
                "ln_gamma": jnp.ones((c.hidden,), dtype),
                "ln_beta": jnp.zeros((c.hidden,), dtype),
            },
            "mlm": {
                "W": trunc(ks[3], (c.hidden, c.hidden)),
                "b": jnp.zeros((c.hidden,), dtype),
                "ln_gamma": jnp.ones((c.hidden,), dtype),
                "ln_beta": jnp.zeros((c.hidden,), dtype),
                # decoder shares the word embedding; only a bias is learned
                "out_b": jnp.zeros((c.vocab_size,), dtype),
            },
        }
        if c.use_nsp:
            params["pooler"] = {
                "W": trunc(ks[4], (c.hidden, c.hidden)),
                "b": jnp.zeros((c.hidden,), dtype),
            }
            params["nsp"] = {
                "W": trunc(ks[5], (c.hidden, 2)),
                "b": jnp.zeros((2,), dtype),
            }
        for i in range(c.num_layers):
            p, _ = self._block.init(ks[8 + i], (c.max_position, c.hidden), dtype)
            params[f"layer_{i}"] = p
        return {"params": params, "state": {}}

    # -- pure functions ----------------------------------------------------

    def encode(self, params, features, *, train=False, rng=None):
        """Token/segment ids → contextual embeddings [N,T,H]."""
        c = self.config
        ids = features["token_ids"]
        seg = features.get("segment_ids")
        mask = features.get("mask")
        t = ids.shape[1]
        emb = params["embeddings"]
        x = opsnn.embedding_lookup(emb["word"], ids)
        x = x + emb["position"][:t][None, :, :]
        if seg is not None:
            x = x + opsnn.embedding_lookup(emb["type"], seg)
        x = opsnn.layer_norm(x, emb["ln_gamma"], emb["ln_beta"], eps=c.eps)
        if train and c.dropout > 0.0 and rng is not None:
            x = opsnn.dropout(x, c.dropout, jax.random.fold_in(rng, 999))
        for i in range(c.num_layers):
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            x, _ = self._block.apply(
                params[f"layer_{i}"], {}, x, train=train, rng=lrng, mask=mask
            )
        return x

    def apply(self, variables, features, *, train=False, rng=None):
        """Returns (hidden_states [N,T,H], state). Feature dict or raw ids."""
        if not isinstance(features, dict):
            features = {"token_ids": features}
        x = self.encode(variables["params"], features, train=train, rng=rng)
        return x, variables.get("state", {})

    def mlm_logits(self, params, hidden):
        c = self.config
        m = params["mlm"]
        h = opsnn.linear(hidden, m["W"], m["b"])
        h = get_activation(c.activation)(h)
        h = opsnn.layer_norm(h, m["ln_gamma"], m["ln_beta"], eps=c.eps)
        return jnp.einsum("nth,vh->ntv", h, params["embeddings"]["word"]) + m["out_b"]

    def nsp_logits(self, params, hidden):
        pooled = jnp.tanh(
            opsnn.linear(hidden[:, 0, :], params["pooler"]["W"], params["pooler"]["b"])
        )
        return opsnn.linear(pooled, params["nsp"]["W"], params["nsp"]["b"])

    def loss_fn(self, params, state, batch, rng=None):
        c = self.config
        features = batch["features"]
        labels = batch["labels"]
        hidden = self.encode(params, features, train=True, rng=rng)

        if "mlm_positions" in labels:
            # Gathered head: decoder GEMM over the P masked slots only.
            pos = labels["mlm_positions"]  # [N,P] int32
            gathered = jnp.take_along_axis(
                hidden, pos[:, :, None], axis=1)  # [N,P,H]
            logits = self.mlm_logits(params, gathered)  # [N,P,V]
            mlm_mask = labels["mlm_weights"].astype(jnp.float32)
        else:
            logits = self.mlm_logits(params, hidden)  # [N,T,V]
            mlm_mask = labels["mlm_mask"].astype(jnp.float32)
        per_tok = losses.sparse_softmax_cross_entropy(
            logits, labels["mlm_labels"], reduction="none"
        )  # [N,T] or [N,P]
        denom = jnp.maximum(jnp.sum(mlm_mask), 1.0)
        mlm_loss = jnp.sum(per_tok * mlm_mask) / denom
        metrics = {"mlm_loss": mlm_loss}
        total = mlm_loss

        if c.use_nsp and "nsp" in labels:
            nsp = losses.sparse_softmax_cross_entropy(
                self.nsp_logits(params, hidden), labels["nsp"]
            )
            metrics["nsp_loss"] = nsp
            total = total + nsp
        metrics["loss"] = total
        return total, (state, metrics)

    def num_params(self, variables) -> int:
        return sum(p.size for p in jax.tree_util.tree_leaves(variables["params"]))


def bert_base(**kw) -> Bert:
    """BERT-base-uncased dims (12L/768H/12A) — north-star config #4."""
    return Bert(BertConfig(**kw))


def bert_tiny(**kw) -> Bert:
    """2L/128H/2A toy config for tests and CPU dry-runs."""
    kw.setdefault("hidden", 128)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("intermediate", 512)
    kw.setdefault("vocab_size", 1000)
    kw.setdefault("max_position", 128)
    return Bert(BertConfig(**kw))


def make_mlm_batch(rng, batch_size, seq_len, vocab_size, *, mask_frac=0.15,
                   mask_id=103, pad_frac=0.0, max_predictions=None):
    """Host-side synthetic MLM batch builder (tests/benchmarks).

    ``max_predictions``: when set, the batch uses the gathered layout —
    ``mlm_positions``/``mlm_weights``/[N,P] ``mlm_labels`` with P =
    max_predictions (masked slots beyond P are UNMASKED again so the dense
    and gathered views of the same batch stay semantically identical).
    """
    import numpy as np

    r = np.random.default_rng(rng)
    ids = r.integers(5, vocab_size, (batch_size, seq_len)).astype(np.int32)
    mlm_mask = (r.random((batch_size, seq_len)) < mask_frac).astype(np.float32)
    attn = np.ones((batch_size, seq_len), np.float32)
    if pad_frac > 0:
        lens = r.integers(int(seq_len * (1 - pad_frac)), seq_len + 1, batch_size)
        attn = (np.arange(seq_len)[None, :] < lens[:, None]).astype(np.float32)
        mlm_mask = mlm_mask * attn
    seg = np.zeros((batch_size, seq_len), np.int32)
    nsp = r.integers(0, 2, batch_size).astype(np.int32)

    labels: Dict[str, Any]
    if max_predictions is not None:
        p = int(max_predictions)
        if p <= 0:
            raise ValueError(f"max_predictions must be >= 1, got {p}")
        positions = np.zeros((batch_size, p), np.int32)
        weights = np.zeros((batch_size, p), np.float32)
        plabels = np.zeros((batch_size, p), np.int32)
        for n in range(batch_size):
            idx = np.flatnonzero(mlm_mask[n])
            if len(idx) > p:       # drop overflow AND unmask it
                mlm_mask[n, idx[p:]] = 0.0
                idx = idx[:p]
            positions[n, :len(idx)] = idx
            weights[n, :len(idx)] = 1.0
            plabels[n, :len(idx)] = ids[n, idx]
        labels = {"mlm_labels": plabels, "mlm_positions": positions,
                  "mlm_weights": weights, "nsp": nsp}
    else:
        labels = {"mlm_labels": ids, "mlm_mask": mlm_mask, "nsp": nsp}
    inp = np.where(mlm_mask > 0, mask_id, ids).astype(np.int32)
    return {
        "features": {"token_ids": inp, "segment_ids": seg, "mask": attn},
        "labels": labels,
    }
