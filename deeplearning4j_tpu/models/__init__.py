"""Subpackage."""
