"""LeNet-5 (↔ org.deeplearning4j.zoo.model.LeNet — benchmark config #1).

ref architecture (zoo LeNet): conv5x5x20 → maxpool2 → conv5x5x50 →
maxpool2 → dense500(relu) → softmax output. NHWC here (TPU layout).
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.config import NeuralNetConfiguration, SequentialConfig
from deeplearning4j_tpu.nn.layers import (
    Conv2D,
    Dense,
    Flatten,
    OutputLayer,
    Pooling2D,
)
from deeplearning4j_tpu.nn.model import SequentialModel
from deeplearning4j_tpu.train.updaters import Adam


def lenet_config(
    *,
    num_classes: int = 10,
    input_shape=(28, 28, 1),
    updater=None,
    seed: int = 12345,
) -> SequentialConfig:
    net = NeuralNetConfiguration(
        seed=seed,
        updater=updater if updater is not None else Adam(1e-3),
        weight_init="xavier",
    )
    layers = [
        Conv2D(filters=20, kernel=5, stride=1, padding="SAME", activation="relu"),
        Pooling2D(pool_type="max", window=2),
        Conv2D(filters=50, kernel=5, stride=1, padding="SAME", activation="relu"),
        Pooling2D(pool_type="max", window=2),
        Flatten(),
        Dense(units=500, activation="relu"),
        OutputLayer(units=num_classes, activation="softmax", loss="mcxent"),
    ]
    return SequentialConfig(net=net, layers=layers, input_shape=input_shape)


def lenet(**kw) -> SequentialModel:
    return SequentialModel(lenet_config(**kw))
