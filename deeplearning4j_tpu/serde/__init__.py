"""Subpackage."""
