"""Checkpoint save/restore (↔ org.deeplearning4j.util.ModelSerializer +
CheckpointListener rotation + SameDiff.save).

ref format: zip{configuration.json, coefficients.bin (flat params),
updaterState.bin, normalizer}. TPU-native format: a directory per
checkpoint containing

- ``config.json``   — model architecture (config_to_json; the model is
  reconstructable from this alone, like the reference)
- ``state.npz``     — every TrainState leaf under its pytree path key
- ``meta.json``     — step, tag, framework version, leaf manifest

Arrays are pulled to host and stored dense (single-host). The layout is
topology-independent: restore does NOT care how the arrays were sharded at
save time — pass a sharding to ``restore_checkpoint`` and leaves are
device_put to it (↔ SURVEY §5.4 'resharding on restore'). Multi-host async
checkpointing can later swap this backend for orbax without changing
callers.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from pathlib import Path
from typing import Any, Dict, Optional

import jax
import numpy as np

from deeplearning4j_tpu.nn.config import config_from_json, config_to_json
from deeplearning4j_tpu.utils.pytree import flatten_with_names
from deeplearning4j_tpu.version import __version__

_INDEX = "checkpoint_index.json"


def _is_key_array(x) -> bool:
    return isinstance(x, jax.Array) and jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key)


def _snapshot_tree(tree: Any):
    """Device→host snapshot of a pytree: (arrays dict, key metadata).

    This is the part of a save that MUST run before training continues —
    donated buffers from the snapshotted state become invalid at the next
    step — and it is cheap next to the file IO that follows."""
    arrays: Dict[str, np.ndarray] = {}
    key_paths = []
    key_impls: Dict[str, str] = {}
    for name, leaf in flatten_with_names(tree):
        if _is_key_array(leaf):
            arrays[name] = np.asarray(jax.random.key_data(leaf))
            key_paths.append(name)
            # impl must round-trip explicitly: rbg key data is uint32[4]
            # and threefry's uint32[2]; wrap_key_data with the default
            # impl would misread a non-default key's data.
            key_impls[name] = str(jax.random.key_impl(leaf))
        else:
            arrays[name] = np.asarray(jax.device_get(leaf))
    return arrays, key_paths, key_impls


def _write_snapshot(directory: str | Path, arrays: Dict[str, np.ndarray],
                    key_paths, key_impls, extra_meta: Optional[dict] = None):
    """File-IO half of a save; safe to run off-thread (touches no jax)."""
    d = Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    np.savez(d / "state.npz", **arrays)
    meta = {
        "version": __version__,
        "time": time.time(),
        "leaves": sorted(arrays.keys()),
        "key_paths": key_paths,
        "key_impls": key_impls,
    }
    if extra_meta:
        meta.update(extra_meta)
    (d / "meta.json").write_text(json.dumps(meta, indent=2))


def save_state_tree(directory: str | Path, tree: Any, extra_meta: Optional[dict] = None):
    """Save any pytree (TrainState, variables dict, …) to directory."""
    _write_snapshot(directory, *_snapshot_tree(tree), extra_meta=extra_meta)


def load_state_tree(directory: str | Path, template: Any, sharding=None,
                    alias=None) -> Any:
    """Restore a pytree saved by save_state_tree into template's structure.

    ``sharding``: optional pytree of shardings (or one sharding) — leaves
    are device_put accordingly (topology-independent resharding).
    ``alias``: optional ``name -> [candidate names]`` callable; the first
    candidate present in the checkpoint is loaded (lets a template read
    leaves saved under a different prefix, e.g. serving's ``state/`` vs a
    TrainState's ``model_state/``).
    """
    d = Path(directory)
    meta = json.loads((d / "meta.json").read_text())
    key_paths = set(meta.get("key_paths", []))
    key_impls = meta.get("key_impls", {})
    with np.load(d / "state.npz") as z:
        data = {k: z[k] for k in z.files}
    paths = jax.tree_util.tree_flatten_with_path(template)[0]
    treedef = jax.tree_util.tree_structure(template)
    from deeplearning4j_tpu.utils.pytree import path_str

    leaves = []
    for p, tmpl_leaf in paths:
        name = path_str(p)
        candidates = [name] if alias is None else list(alias(name))
        hit = next((c for c in candidates if c in data), None)
        if hit is None:
            tried = f" (tried {candidates})" if len(candidates) > 1 else ""
            raise KeyError(f"checkpoint missing leaf '{name}'{tried}")
        arr = data[hit]
        if hit in key_paths:
            leaves.append(jax.random.wrap_key_data(
                jax.numpy.asarray(arr), impl=key_impls.get(hit)))
        else:
            leaves.append(jax.numpy.asarray(arr).astype(tmpl_leaf.dtype))
    tree = jax.tree_util.tree_unflatten(treedef, leaves)
    if sharding is not None:
        tree = _place(tree, sharding)
    return tree


def _place(tree, sharding):
    """device_put a restored tree onto shardings, including MULTI-PROCESS
    (non-addressable) meshes.

    Plain ``jax.device_put`` refuses shardings whose devices span processes
    (SURVEY §5.3-§5.4: restore-on-a-different-topology is the recovery
    story, and that topology is usually multi-host). Non-addressable
    placement: ordinary leaves go through ``jax.make_array_from_callback``
    (each process materializes only its addressable shards from the
    host-loaded global value); PRNG-key leaves — tiny — are rebuilt inside
    a jit whose out_shardings does the placement.
    """
    def put(leaf, s):
        if s.is_fully_addressable:
            return jax.device_put(leaf, s)
        if _is_key_array(leaf):
            data = np.asarray(jax.random.key_data(leaf))
            impl = str(jax.random.key_impl(leaf))
            return jax.jit(
                lambda: jax.random.wrap_key_data(
                    jax.numpy.asarray(data), impl=impl),
                out_shardings=s)()
        host = np.asarray(leaf)
        return jax.make_array_from_callback(
            host.shape, s, lambda idx: host[idx])

    if isinstance(sharding, jax.sharding.Sharding):
        return jax.tree_util.tree_map(lambda l: put(l, sharding), tree)
    return jax.tree_util.tree_map(put, tree, sharding)


def _model_config_json(model) -> str:
    """Architecture record for config.json. SequentialConfig/GraphConfig
    carry their own to_json; plain registered config dataclasses
    (BertConfig, GptConfig, ...) serialize through the registry — every
    model kind checkpoints, not just the containers."""
    cfg = model.config
    if hasattr(cfg, "to_json"):
        return cfg.to_json()
    return config_to_json(cfg)


_index_lock = threading.Lock()


def _finalize_checkpoint(root: Path, name: str, step: int, tag: str,
                         keep_last: int, config_json: Optional[str]):
    """config.json + rotation-index update for a written checkpoint dir.
    Runs wherever the write ran (caller thread or async worker) so index
    order matches write-completion order.

    The index read-modify-write (and rotation deletes) are serialized by
    a process-wide lock: a synchronous ``save_checkpoint`` — e.g. a
    SIGTERM PreemptionCheckpointer — can legitimately race an in-flight
    ``AsyncCheckpointer`` worker writing to the same directory, and an
    unguarded update could drop an index entry or rotate-delete a
    checkpoint mid-write. Cross-PROCESS writers to one directory remain
    unsupported (single-writer-per-directory, matching orbax)."""
    ckpt_dir = root / name
    if config_json is not None:
        (ckpt_dir / "config.json").write_text(config_json)
    with _index_lock:
        idx_path = root / _INDEX
        index = json.loads(idx_path.read_text()) if idx_path.exists() else {"checkpoints": []}
        index["checkpoints"].append({"name": name, "step": step, "tag": tag, "time": time.time()})
        if keep_last and len(index["checkpoints"]) > keep_last:
            for old in index["checkpoints"][:-keep_last]:
                shutil.rmtree(root / old["name"], ignore_errors=True)
            index["checkpoints"] = index["checkpoints"][-keep_last:]
        # atomic replace: a SIGKILL mid-write must leave the previous
        # index readable, or restart recovery loses ALL checkpoints
        tmp = idx_path.with_suffix(".tmp")
        tmp.write_text(json.dumps(index, indent=2))
        os.replace(tmp, idx_path)
    return str(ckpt_dir)


def save_checkpoint(directory: str | Path, train_state, *, model=None,
                    tag: str = "", keep_last: int = 0):
    """Full training checkpoint: state + model config + rotation index
    (↔ CheckpointListener.keepLast + checkpoint.json)."""
    root = Path(directory)
    root.mkdir(parents=True, exist_ok=True)
    step = int(jax.device_get(train_state.step))
    name = f"checkpoint_{step}" + (f"_{tag}" if tag else "")
    save_state_tree(root / name, train_state, {"step": step, "tag": tag})
    return _finalize_checkpoint(
        root, name, step, tag, keep_last,
        _model_config_json(model) if model is not None else None)


class AsyncCheckpointer:
    """Orbax-style asynchronous checkpointing (SURVEY §5.4's stated TPU
    equivalent: "orbax-style sharded async checkpoint").

    The device→host snapshot runs synchronously on the caller's thread —
    it must, because the trainer donates state buffers and step N's state
    is gone by step N+1 — but serialization, file IO, and rotation run on
    a single background worker, so a multi-GB checkpoint costs the train
    loop a D2H copy instead of a disk write. Semantics follow orbax's
    AsyncCheckpointer: one save in flight at a time (a new ``save`` first
    waits out the previous one), ``wait_until_finished`` joins, and a
    failed write re-raises on the next ``save``/``wait_until_finished``
    rather than being dropped silently.

    Usable directly or through ``CheckpointListener(async_save=True)``.
    """

    def __init__(self):
        import concurrent.futures

        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ckpt-writer")
        self._inflight = None

    def save(self, directory: str | Path, train_state, *, model=None,
             tag: str = "", keep_last: int = 0) -> str:
        """Snapshot now, write in the background; returns the checkpoint
        path that WILL exist once the write completes."""
        self.wait_until_finished()
        root = Path(directory)
        root.mkdir(parents=True, exist_ok=True)
        step = int(jax.device_get(train_state.step))
        name = f"checkpoint_{step}" + (f"_{tag}" if tag else "")
        snapshot = _snapshot_tree(train_state)
        config_json = (_model_config_json(model) if model is not None
                       else None)

        def _write():
            _write_snapshot(root / name, *snapshot,
                            extra_meta={"step": step, "tag": tag})
            _finalize_checkpoint(root, name, step, tag, keep_last,
                                 config_json)

        self._inflight = self._pool.submit(_write)
        return str(root / name)

    def wait_until_finished(self):
        """Join the in-flight write, re-raising any worker exception."""
        if self._inflight is not None:
            fut, self._inflight = self._inflight, None
            fut.result()

    def close(self):
        try:
            self.wait_until_finished()
        finally:
            self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def latest_checkpoint(directory: str | Path) -> Optional[str]:
    idx_path = Path(directory) / _INDEX
    if not idx_path.exists():
        return None
    index = json.loads(idx_path.read_text())
    if not index["checkpoints"]:
        return None
    return str(Path(directory) / index["checkpoints"][-1]["name"])


def restore_checkpoint(ckpt_dir: str | Path, train_state_template,
                       sharding=None):
    """↔ ModelSerializer.restoreMultiLayerNetwork(+updater): returns the
    restored TrainState."""
    return load_state_tree(ckpt_dir, train_state_template, sharding)


def load_model_config(ckpt_dir: str | Path):
    """Rebuild the model config from a checkpoint's config.json."""
    return config_from_json((Path(ckpt_dir) / "config.json").read_text())


def load_inference_variables(ckpt_dir: str | Path, model) -> Any:
    """Inference variables ``{"params", "state"}`` from a checkpoint.

    Serving-side loader (serving/registry.py): accepts both checkpoint
    flavors — a full TrainState (leaves ``params/...``,
    ``model_state/...``) and a bare variables dict (``params/...``,
    ``state/...``) — and drops optimizer state, step, and RNG, which
    inference never needs. ``model.init()`` provides the target structure
    and leaf dtypes."""
    def alias(name):
        if name.startswith("state/"):
            return [name, "model_state/" + name[len("state/"):]]
        return [name]

    return load_state_tree(ckpt_dir, model.init(), alias=alias)
