"""Checkpoint save/restore (↔ org.deeplearning4j.util.ModelSerializer +
CheckpointListener rotation + SameDiff.save).

ref format: zip{configuration.json, coefficients.bin (flat params),
updaterState.bin, normalizer}. TPU-native format: a directory per
checkpoint containing

- ``config.json``   — model architecture (config_to_json; the model is
  reconstructable from this alone, like the reference)
- ``state.npz``     — every TrainState leaf under its pytree path key
- ``meta.json``     — step, tag, framework version, leaf manifest

Arrays are pulled to host and stored dense (single-host). The layout is
topology-independent: restore does NOT care how the arrays were sharded at
save time — pass a sharding to ``restore_checkpoint`` and leaves are
device_put to it (↔ SURVEY §5.4 'resharding on restore'). Multi-host async
checkpointing can later swap this backend for orbax without changing
callers.

Integrity (resilience layer): every snapshot carries a ``manifest.json``
with a per-array SHA-256 digest plus the whole-file digest/size of
``state.npz``; all files land via tmp-sibling + ``os.replace`` so a crash
at any point leaves either the previous complete state or tmp litter —
never a truncated file at a final path. ``verify_checkpoint`` checks a
directory against its manifest; ``latest_verified_checkpoint`` walks the
rotation index newest→oldest past corrupt/truncated/missing entries
(quarantining the bad ones) — the restore path recovery code uses.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from deeplearning4j_tpu.nn.config import config_from_json, config_to_json
from deeplearning4j_tpu.utils.pytree import flatten_with_names
from deeplearning4j_tpu.version import __version__

_INDEX = "checkpoint_index.json"
_MANIFEST = "manifest.json"
_QUARANTINE_DIR = "quarantine"


def _is_key_array(x) -> bool:
    return isinstance(x, jax.Array) and jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key)


def _snapshot_tree(tree: Any):
    """Device→host snapshot of a pytree: (arrays dict, key metadata).

    This is the part of a save that MUST run before training continues —
    donated buffers from the snapshotted state become invalid at the next
    step — and it is cheap next to the file IO that follows."""
    arrays: Dict[str, np.ndarray] = {}
    key_paths = []
    key_impls: Dict[str, str] = {}
    for name, leaf in flatten_with_names(tree):
        if _is_key_array(leaf):
            arrays[name] = np.asarray(jax.random.key_data(leaf))
            key_paths.append(name)
            # impl must round-trip explicitly: rbg key data is uint32[4]
            # and threefry's uint32[2]; wrap_key_data with the default
            # impl would misread a non-default key's data.
            key_impls[name] = str(jax.random.key_impl(leaf))
        else:
            arrays[name] = np.asarray(jax.device_get(leaf))
    try:
        from deeplearning4j_tpu.observability.runtime import record_transfer

        record_transfer("d2h", sum(a.nbytes for a in arrays.values()))
    except Exception:  # noqa: BLE001 - telemetry never fails a snapshot
        pass
    return arrays, key_paths, key_impls


def atomic_write_text(path: Path, text: str):
    """tmp-sibling + os.replace: readers never observe a partial file.

    Shared by every integrity manifest in the repo (checkpoint
    manifests here, the compile-cache manifest in
    runtime/compilecache.py, the warmup manifest in
    serving/warmstart.py) — one crash-consistency idiom, not three."""
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(text)
    os.replace(tmp, path)


_atomic_write_text = atomic_write_text


def _array_sha256(a: np.ndarray) -> str:
    """Content digest of one array (dtype + shape + raw bytes)."""
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(repr(tuple(a.shape)).encode())
    h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def file_sha256(path: Path) -> str:
    """Streaming whole-file SHA-256 (the manifest digest primitive)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


_file_sha256 = file_sha256


def _fault_injector():
    """Active process-wide fault injector, or None (the common case)."""
    from deeplearning4j_tpu.resilience.faults import get_fault_injector

    inj = get_fault_injector()
    return inj if inj.enabled else None


def _ckpt_metrics():
    """Shared-registry checkpoint bundle, or None when instrumentation is
    off (observability/metrics.py)."""
    from deeplearning4j_tpu.observability import metrics as _obsm

    return _obsm.get_checkpoint_metrics() if _obsm.enabled() else None


def _observe_op(op: str, seconds: float):
    m = _ckpt_metrics()
    if m is not None:
        m.op_seconds.observe(seconds, op=op)


def _write_snapshot(directory: str | Path, arrays: Dict[str, np.ndarray],
                    key_paths, key_impls, extra_meta: Optional[dict] = None):
    """File-IO half of a save; safe to run off-thread (touches no jax).

    Crash-consistent write order: (1) ``state.npz`` to a tmp sibling, then
    ``os.replace`` — a SIGKILL mid-write leaves the previous complete file
    (or tmp litter), never a truncated ``state.npz`` at the final path;
    (2) ``manifest.json`` (per-array SHA-256 + whole-file digest of the
    bytes just written); (3) ``meta.json`` last. The caller indexes only
    after this returns, so an indexed checkpoint always has its manifest.
    """
    t_op = time.perf_counter()
    d = Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    inj = _fault_injector()
    npz = d / "state.npz"
    tmp = d / "state.npz.tmp"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        file_digest = _file_sha256(tmp)
        file_size = tmp.stat().st_size
        if inj is not None:
            # the classic torn-write window: after the bytes, before the
            # rename (mode="kill" SIGKILLs here for crash-consistency tests)
            inj.maybe_fail("checkpoint.write_crash")
        os.replace(tmp, npz)
    finally:
        tmp.unlink(missing_ok=True)
    if inj is not None and inj.fire("checkpoint.corrupt") is not None:
        # simulate bit-rot / out-of-band truncation of a checkpoint the
        # index will point at — verify_checkpoint must catch it on restore
        with open(npz, "r+b") as f:
            f.truncate(max(file_size // 2, 1))
    manifest = {
        "state_npz": {"sha256": file_digest, "size": file_size},
        "arrays": {
            name: {"sha256": _array_sha256(a), "dtype": str(a.dtype),
                   "shape": list(a.shape)}
            for name, a in arrays.items()
        },
    }
    _atomic_write_text(d / _MANIFEST, json.dumps(manifest, indent=2))
    meta = {
        "version": __version__,
        "time": time.time(),
        "leaves": sorted(arrays.keys()),
        "key_paths": key_paths,
        "key_impls": key_impls,
    }
    if extra_meta:
        meta.update(extra_meta)
    _atomic_write_text(d / "meta.json", json.dumps(meta, indent=2))
    _observe_op("save", time.perf_counter() - t_op)


def save_state_tree(directory: str | Path, tree: Any, extra_meta: Optional[dict] = None):
    """Save any pytree (TrainState, variables dict, …) to directory."""
    _write_snapshot(directory, *_snapshot_tree(tree), extra_meta=extra_meta)


def load_state_tree(directory: str | Path, template: Any, sharding=None,
                    alias=None) -> Any:
    """Restore a pytree saved by save_state_tree into template's structure.

    ``sharding``: optional pytree of shardings (or one sharding) — leaves
    are device_put accordingly (topology-independent resharding).
    ``alias``: optional ``name -> [candidate names]`` callable; the first
    candidate present in the checkpoint is loaded (lets a template read
    leaves saved under a different prefix, e.g. serving's ``state/`` vs a
    TrainState's ``model_state/``).
    """
    t_op = time.perf_counter()
    d = Path(directory)
    meta = json.loads((d / "meta.json").read_text())
    key_paths = set(meta.get("key_paths", []))
    key_impls = meta.get("key_impls", {})
    with np.load(d / "state.npz") as z:
        data = {k: z[k] for k in z.files}
    paths = jax.tree_util.tree_flatten_with_path(template)[0]
    treedef = jax.tree_util.tree_structure(template)
    from deeplearning4j_tpu.utils.pytree import path_str

    leaves = []
    for p, tmpl_leaf in paths:
        name = path_str(p)
        candidates = [name] if alias is None else list(alias(name))
        hit = next((c for c in candidates if c in data), None)
        if hit is None:
            tried = f" (tried {candidates})" if len(candidates) > 1 else ""
            raise KeyError(f"checkpoint missing leaf '{name}'{tried}")
        arr = data[hit]
        if hit in key_paths:
            leaves.append(jax.random.wrap_key_data(
                jax.numpy.asarray(arr), impl=key_impls.get(hit)))
        else:
            leaves.append(jax.numpy.asarray(arr).astype(tmpl_leaf.dtype))
    tree = jax.tree_util.tree_unflatten(treedef, leaves)
    if sharding is not None:
        tree = _place(tree, sharding)
    _observe_op("restore", time.perf_counter() - t_op)
    try:
        from deeplearning4j_tpu.observability.runtime import record_transfer

        record_transfer("h2d", sum(a.nbytes for a in data.values()))
    except Exception:  # noqa: BLE001 - telemetry never fails a restore
        pass
    return tree


def _place(tree, sharding):
    """device_put a restored tree onto shardings, including MULTI-PROCESS
    (non-addressable) meshes.

    Plain ``jax.device_put`` refuses shardings whose devices span processes
    (SURVEY §5.3-§5.4: restore-on-a-different-topology is the recovery
    story, and that topology is usually multi-host). Non-addressable
    placement: ordinary leaves go through ``jax.make_array_from_callback``
    (each process materializes only its addressable shards from the
    host-loaded global value); PRNG-key leaves — tiny — are rebuilt inside
    a jit whose out_shardings does the placement.
    """
    def put(leaf, s):
        if s.is_fully_addressable:
            return jax.device_put(leaf, s)
        if _is_key_array(leaf):
            data = np.asarray(jax.random.key_data(leaf))
            impl = str(jax.random.key_impl(leaf))
            return jax.jit(
                lambda: jax.random.wrap_key_data(
                    jax.numpy.asarray(data), impl=impl),
                out_shardings=s)()
        host = np.asarray(leaf)
        return jax.make_array_from_callback(
            host.shape, s, lambda idx: host[idx])

    if isinstance(sharding, jax.sharding.Sharding):
        return jax.tree_util.tree_map(lambda l: put(l, sharding), tree)
    return jax.tree_util.tree_map(put, tree, sharding)


def _model_config_json(model) -> str:
    """Architecture record for config.json. SequentialConfig/GraphConfig
    carry their own to_json; plain registered config dataclasses
    (BertConfig, GptConfig, ...) serialize through the registry — every
    model kind checkpoints, not just the containers."""
    cfg = model.config
    if hasattr(cfg, "to_json"):
        return cfg.to_json()
    return config_to_json(cfg)


_index_lock = threading.Lock()


def _finalize_checkpoint(root: Path, name: str, step: int, tag: str,
                         keep_last: int, config_json: Optional[str]):
    """config.json + rotation-index update for a written checkpoint dir.
    Runs wherever the write ran (caller thread or async worker) so index
    order matches write-completion order.

    The index read-modify-write (and rotation deletes) are serialized by
    a process-wide lock: a synchronous ``save_checkpoint`` — e.g. a
    SIGTERM PreemptionCheckpointer — can legitimately race an in-flight
    ``AsyncCheckpointer`` worker writing to the same directory, and an
    unguarded update could drop an index entry or rotate-delete a
    checkpoint mid-write. Cross-PROCESS writers to one directory remain
    unsupported (single-writer-per-directory, matching orbax)."""
    ckpt_dir = root / name
    if config_json is not None:
        _atomic_write_text(ckpt_dir / "config.json", config_json)
    # analysis: allow(blocking-under-lock) — the index lock exists to
    # serialize exactly this read-modify-write + rotation-delete (see
    # docstring); it is a leaf lock, nothing nests inside it
    with _index_lock:
        idx_path = root / _INDEX
        index = json.loads(idx_path.read_text()) if idx_path.exists() else {"checkpoints": []}
        # re-save of the same name (a rolled-back run repeating a step):
        # the write replaced the directory contents, so the old entry is
        # stale — drop it or rotation could rmtree a live checkpoint that
        # a duplicate entry still references
        index["checkpoints"] = [c for c in index["checkpoints"]
                                if c.get("name") != name]
        index["checkpoints"].append({"name": name, "step": step, "tag": tag, "time": time.time()})
        if keep_last and len(index["checkpoints"]) > keep_last:
            for old in index["checkpoints"][:-keep_last]:
                shutil.rmtree(root / old["name"], ignore_errors=True)
            index["checkpoints"] = index["checkpoints"][-keep_last:]
        # atomic replace: a SIGKILL mid-write must leave the previous
        # index readable, or restart recovery loses ALL checkpoints
        tmp = idx_path.with_suffix(".tmp")
        tmp.write_text(json.dumps(index, indent=2))
        os.replace(tmp, idx_path)
    return str(ckpt_dir)


def save_checkpoint(directory: str | Path, train_state, *, model=None,
                    tag: str = "", keep_last: int = 0,
                    extra_meta: Optional[dict] = None):
    """Full training checkpoint: state + model config + rotation index
    (↔ CheckpointListener.keepLast + checkpoint.json). ``extra_meta``
    merges into meta.json (recovery stores its epoch/batch position)."""
    root = Path(directory)
    root.mkdir(parents=True, exist_ok=True)
    step = int(jax.device_get(train_state.step))
    name = f"checkpoint_{step}" + (f"_{tag}" if tag else "")
    meta = {"step": step, "tag": tag}
    if extra_meta:
        meta.update(extra_meta)
    save_state_tree(root / name, train_state, meta)
    return _finalize_checkpoint(
        root, name, step, tag, keep_last,
        _model_config_json(model) if model is not None else None)


class AsyncCheckpointer:
    """Orbax-style asynchronous checkpointing (SURVEY §5.4's stated TPU
    equivalent: "orbax-style sharded async checkpoint").

    The device→host snapshot runs synchronously on the caller's thread —
    it must, because the trainer donates state buffers and step N's state
    is gone by step N+1 — but serialization, file IO, and rotation run on
    a single background worker, so a multi-GB checkpoint costs the train
    loop a D2H copy instead of a disk write. Semantics follow orbax's
    AsyncCheckpointer: one save in flight at a time (a new ``save`` first
    waits out the previous one), ``wait_until_finished`` joins, and a
    failed write re-raises on the next ``save``/``wait_until_finished``
    rather than being dropped silently.

    Usable directly or through ``CheckpointListener(async_save=True)``.
    """

    def __init__(self):
        import concurrent.futures

        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ckpt-writer")
        self._inflight = None

    def save(self, directory: str | Path, train_state, *, model=None,
             tag: str = "", keep_last: int = 0,
             extra_meta: Optional[dict] = None) -> str:
        """Snapshot now, write in the background; returns the checkpoint
        path that WILL exist once the write completes."""
        self.wait_until_finished()
        root = Path(directory)
        root.mkdir(parents=True, exist_ok=True)
        step = int(jax.device_get(train_state.step))
        name = f"checkpoint_{step}" + (f"_{tag}" if tag else "")
        snapshot = _snapshot_tree(train_state)
        config_json = (_model_config_json(model) if model is not None
                       else None)
        meta = {"step": step, "tag": tag}
        if extra_meta:
            meta.update(extra_meta)

        def _write():
            _write_snapshot(root / name, *snapshot, extra_meta=meta)
            _finalize_checkpoint(root, name, step, tag, keep_last,
                                 config_json)

        self._inflight = self._pool.submit(_write)
        return str(root / name)

    def wait_until_finished(self):
        """Join the in-flight write, re-raising any worker exception."""
        if self._inflight is not None:
            fut, self._inflight = self._inflight, None
            fut.result()

    def close(self):
        try:
            self.wait_until_finished()
        finally:
            self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _read_index_entries(directory: str | Path, *, strict: bool = True) -> list:
    idx_path = Path(directory) / _INDEX
    if not idx_path.exists():
        return []
    try:
        return json.loads(idx_path.read_text()).get("checkpoints", [])
    except Exception:  # noqa: BLE001 - out-of-band index corruption
        if strict:
            raise
        return []


def latest_checkpoint(directory: str | Path) -> Optional[str]:
    """Newest indexed checkpoint whose directory still exists. Entries
    whose directory was deleted out-of-band (operator cleanup, quarantine)
    are skipped instead of handed to a restore that would crash."""
    root = Path(directory)
    for entry in reversed(_read_index_entries(root)):
        d = root / str(entry.get("name", ""))
        if d.is_dir():
            return str(d)
    return None


def verify_checkpoint(ckpt_dir: str | Path, *,
                      deep: bool = False) -> Tuple[bool, str]:
    """Integrity-check one checkpoint directory against its manifest.

    Returns ``(ok, reason)``. The default check compares ``state.npz``'s
    size and whole-file SHA-256 to the manifest — any flipped or missing
    byte fails it. ``deep=True`` additionally re-hashes every array
    against the per-array digests (catches a manifest that matches the
    file but disagrees with itself, and names the bad leaf). Checkpoints
    written before manifests existed verify as ok with a "legacy" reason —
    fallback must not quarantine every pre-upgrade checkpoint.
    """
    t_op = time.perf_counter()
    try:
        ok, reason = _verify_checkpoint_impl(ckpt_dir, deep=deep)
        if not ok:
            try:
                from deeplearning4j_tpu.observability.flightrecorder import (
                    record_event,
                )

                record_event("checkpoint.verify_failed",
                             checkpoint=str(ckpt_dir), reason=reason)
            except Exception:  # noqa: BLE001 - never mask the verdict
                pass
        return ok, reason
    finally:
        _observe_op("verify", time.perf_counter() - t_op)


def _verify_checkpoint_impl(ckpt_dir: str | Path, *,
                            deep: bool = False) -> Tuple[bool, str]:
    d = Path(ckpt_dir)
    if not d.is_dir():
        return False, "missing checkpoint directory"
    npz = d / "state.npz"
    if not npz.is_file():
        return False, "missing state.npz"
    try:
        json.loads((d / "meta.json").read_text())
    except FileNotFoundError:
        return False, "missing meta.json"
    except Exception as e:  # noqa: BLE001 - torn/garbled json
        return False, f"unreadable meta.json: {e}"
    man_path = d / _MANIFEST
    if not man_path.is_file():
        return True, "legacy checkpoint (no manifest); integrity unverified"
    try:
        manifest = json.loads(man_path.read_text())
    except Exception as e:  # noqa: BLE001
        return False, f"unreadable manifest.json: {e}"
    want = manifest.get("state_npz", {})
    size = npz.stat().st_size
    if want.get("size") is not None and size != want["size"]:
        return False, (f"state.npz size {size} != manifest {want['size']} "
                       "(truncated write?)")
    if want.get("sha256") and _file_sha256(npz) != want["sha256"]:
        return False, "state.npz sha256 mismatch (corrupt bytes)"
    if deep:
        arrays_man = manifest.get("arrays", {})
        try:
            with np.load(npz) as z:
                if set(z.files) != set(arrays_man):
                    return False, "leaf set differs from manifest"
                for name, rec in arrays_man.items():
                    if _array_sha256(z[name]) != rec.get("sha256"):
                        return False, f"array '{name}' sha256 mismatch"
        except Exception as e:  # noqa: BLE001 - undecodable zip
            return False, f"unreadable state.npz: {e}"
    return True, "ok"


def quarantine_checkpoint(ckpt_dir: str | Path,
                          reason: str = "") -> Optional[str]:
    """Move a corrupt checkpoint into ``<root>/quarantine/`` (same-fs
    atomic rename) instead of deleting evidence; returns the new path or
    None if the move failed. A QUARANTINE.txt records why."""
    d = Path(ckpt_dir)
    qdir = d.parent / _QUARANTINE_DIR
    qdir.mkdir(parents=True, exist_ok=True)
    target = qdir / d.name
    n = 0
    while target.exists():
        n += 1
        target = qdir / f"{d.name}.{n}"
    try:
        os.replace(d, target)
    except OSError:
        return None
    m = _ckpt_metrics()
    if m is not None:
        m.quarantined_total.inc()
    try:
        from deeplearning4j_tpu.observability.flightrecorder import (
            record_event,
        )

        record_event("checkpoint.quarantined", checkpoint=str(ckpt_dir),
                     quarantine=str(target), reason=reason[:300])
    except Exception:  # noqa: BLE001 - telemetry never blocks quarantine
        pass
    try:
        (target / "QUARANTINE.txt").write_text(
            f"quarantined {time.time()}: {reason}\n")
    except OSError:
        pass
    return str(target)


def latest_verified_checkpoint(directory: str | Path, *,
                               quarantine: bool = True,
                               deep: bool = False) -> Optional[str]:
    """The restore path recovery trusts: walk the rotation index newest →
    oldest and return the first checkpoint that passes
    :func:`verify_checkpoint`. Missing directories are skipped; corrupt
    ones are quarantined (moved aside so the next walk doesn't re-hash
    them and operators can post-mortem). Never raises on bad on-disk
    state — an unreadable index just means no verified checkpoint."""
    root = Path(directory)
    try:
        entries = _read_index_entries(root, strict=False)
    except Exception:  # noqa: BLE001 - unreachable, strict=False absorbs
        return None
    for entry in reversed(entries):
        d = root / str(entry.get("name", ""))
        if not d.is_dir():
            continue
        ok, why = verify_checkpoint(d, deep=deep)
        if ok:
            return str(d)
        if quarantine:
            quarantine_checkpoint(d, reason=why)
    return None


def restore_checkpoint(ckpt_dir: str | Path, train_state_template,
                       sharding=None):
    """↔ ModelSerializer.restoreMultiLayerNetwork(+updater): returns the
    restored TrainState."""
    return load_state_tree(ckpt_dir, train_state_template, sharding)


def load_model_config(ckpt_dir: str | Path):
    """Rebuild the model config from a checkpoint's config.json."""
    return config_from_json((Path(ckpt_dir) / "config.json").read_text())


def load_inference_variables(ckpt_dir: str | Path, model) -> Any:
    """Inference variables ``{"params", "state"}`` from a checkpoint.

    Serving-side loader (serving/registry.py): accepts both checkpoint
    flavors — a full TrainState (leaves ``params/...``,
    ``model_state/...``) and a bare variables dict (``params/...``,
    ``state/...``) — and drops optimizer state, step, and RNG, which
    inference never needs. ``model.init()`` provides the target structure
    and leaf dtypes."""
    def alias(name):
        if name.startswith("state/"):
            return [name, "model_state/" + name[len("state/"):]]
        return [name]

    return load_state_tree(ckpt_dir, model.init(), alias=alias)
